#!/usr/bin/env bash
# Start cruise-control-tpu (reference parity: kafka-cruise-control-start.sh).
# Usage: ./cruise-control-tpu-start.sh [config/cruisecontrol.properties] [port]
set -euo pipefail
base_dir=$(dirname "$0")
config=${1:-"$base_dir/config/cruisecontrol.properties"}
port=${2:-}
# Live mode when the properties set bootstrap.servers; demo otherwise
# (the app auto-selects).
args=(--properties "$config")
[[ -n "$port" ]] && args+=(--port "$port")
mkdir -p "$base_dir/fileStore"
echo $$ > "$base_dir/fileStore/cruise-control-tpu.pid"
exec python -m cruise_control_tpu.api.app "${args[@]}"
