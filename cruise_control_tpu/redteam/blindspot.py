"""Forecaster blind-spot tagging: which mined scenarios could the
round-19 predictive detector NOT have seen coming?

The forecaster's documented negatives — a step change inside the fit
window, a uniform swell the rolling model-mean lags — have so far been
asserted in prose. This module measures them: for each mined near-
violation, rebuild the scenario's GLOBAL load-factor trajectory
analytically (drift wave + ``set_load`` steps — the same formula
``DriftingSampler`` scales every partition by), fit the first half with
the forecaster's own ``project_series``, and check whether the tail the
violation lives in stays inside the fit's residual band. A mined
violation the fit projects correctly was FORESEEABLE (a ramp the trend
basis extrapolates); one outside the band is a measured blind spot —
the step-change negative, now a number in the frontier artifact
instead of a sentence in a docstring.

Determinism (CCSA004): pure functions of the spec — the series is
closed-form, ``project_series`` is a jitted pure fit, and every float
in the report is rounded before it reaches JSON.
"""

from __future__ import annotations

import math
from typing import Mapping, Sequence

from ..testing.simulator import ScenarioSpec

#: Entries with overall margin below this are near-violations worth a
#: blind-spot verdict (mirrors miner.NEAR_MARGIN; kept separate so this
#: module has no import cycle with the miner).
_NEAR_MARGIN = 0.1

#: The fit must miss by more than max(this many sigmas, _MISS_FLOOR ×
#: the mean history level) to count as a blind spot — one honest
#: threshold, not a tunable to chase a desired count.
_MISS_SIGMAS = 3.0
_MISS_FLOOR = 0.05


def global_factor_series(spec: ScenarioSpec,
                         ticks: int | None = None) -> list[float]:
    """The spec's global load-factor trajectory, closed-form: the
    ``set_load`` step schedule × the diurnal drift wave — exactly the
    global scaling ``DriftingSampler._factor`` applies (per-topic
    hotspots excluded: this is the GLOBAL view the forecaster's
    capacity question cares about)."""
    n = int(ticks if ticks is not None else spec.ticks)
    steps = sorted(((e.tick, float(e.params["factor"]))
                    for e in spec.events if e.kind == "set_load"),
                   key=lambda t: t[0])
    amp = spec.drift.amplitude
    period = max(1.0, float(spec.drift.period_ticks))
    phase = spec.drift.phase_ticks
    out = []
    factor = 1.0
    i = 0
    for t in range(n):
        while i < len(steps) and steps[i][0] <= t:
            factor = steps[i][1]
            i += 1
        drift = 1.0
        if amp:
            drift = 1.0 + amp * math.sin(
                2.0 * math.pi * (t + phase) / period)
        out.append(round(max(factor * drift, 0.01), 6))
    return out


def forecast_miss(series: Sequence[float], split: int,
                  period: int = 0) -> dict:
    """Fit ``series[:split]`` with the forecaster's ``project_series``
    and measure how far the actual tail escapes the projection.
    ``miss=True`` = the trajectory was NOT foreseeable from the fit
    window (deviation beyond the residual band) — the blind-spot
    verdict."""
    import jax.numpy as jnp

    from ..forecast.forecaster import project_series

    split = max(2, min(int(split), len(series) - 1))
    horizon = len(series) - split
    hist = jnp.asarray(series[:split], dtype=jnp.float32)[:, None]
    projected, sigma = project_series(hist, horizon, period)
    proj = [float(v) for v in projected[:, 0]]
    actual = list(series[split:])
    deviation = max(abs(a - p) for a, p in zip(actual, proj))
    mean_level = sum(abs(v) for v in series[:split]) / split
    band = max(_MISS_SIGMAS * float(sigma[0]), _MISS_FLOOR * mean_level)
    return {
        "miss": bool(deviation > band),
        "maxDeviation": round(deviation, 6),
        "band": round(band, 6),
        "split": split,
        "horizon": horizon,
    }


def entry_blind_spot(spec: ScenarioSpec, margin: float) -> dict:
    """One frontier entry's blind-spot verdict: ``tagged`` iff the
    entry is a near-violation (margin < 0.1) AND its global trajectory
    escapes the forecaster's fit band — a worst case the predictive
    detector could not have predicted. Foreseeable near-violations and
    comfortable survivors report the same measurements untagged, so
    the report carries its negatives too."""
    series = global_factor_series(spec)
    split = max(4, len(series) // 2)
    fit = forecast_miss(series, split)
    return {
        "tagged": bool(margin < _NEAR_MARGIN and fit["miss"]),
        "nearViolation": bool(margin < _NEAR_MARGIN),
        **fit,
    }
