"""Frontier persistence: the mined worst-case set as REPLAYABLE specs.

The frontier file is the red team's lasting output — a regression
library the system earned instead of imagined. Every entry is a
``Candidate`` recipe (template, seed, ticks, perturbation list) plus
the score pins its replay must reproduce: ``replay_entry`` rebuilds the
exact ScenarioSpec through ``generator.perturbed_future`` and runs it
full-loop through ``run_scenario``; a byte-different ScenarioScore or a
flipped SLO verdict is a regression (bench's RED_TEAM stage hard-fails
on it).

Format: sorted-keys JSON (2-space indent, trailing newline) so one
mining sweep at one sweep seed writes a byte-identical file — the same
determinism contract every other artifact in this repo carries.
"""

from __future__ import annotations

import json
import os
from typing import Mapping

from .miner import Candidate

#: Where the committed regression frontier lives, relative to the repo
#: root (the ``redteam.frontier.path`` config default).
DEFAULT_FRONTIER_PATH = "fileStore/redteam_frontier.json"


def frontier_json(result: Mapping) -> str:
    """The canonical byte encoding of a mining result (or loaded
    frontier): sorted keys, 2-space indent, one trailing newline."""
    return json.dumps(result, sort_keys=True, indent=2) + "\n"


def save_frontier(result: Mapping, path: str) -> None:
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        f.write(frontier_json(result))


def load_frontier(path: str) -> dict | None:
    """The parsed frontier file, or None when it does not exist yet
    (the miner has never run — callers surface that hint, never
    invent an empty frontier)."""
    try:
        with open(path) as f:
            return json.load(f)
    except OSError:
        return None


def entry_candidate(entry: Mapping) -> Candidate:
    """The replay recipe of one frontier entry."""
    return Candidate.from_dict(entry)


def entry_spec(entry: Mapping):
    """The entry's full-loop ScenarioSpec, rebuilt from the recipe —
    pure, so the same entry dict yields the same spec bytes forever."""
    return entry_candidate(entry).future().spec


def replay_entry(entry: Mapping, seed: int | None = None,
                 ticks: int | None = None,
                 config_overrides: Mapping | None = None):
    """Full-loop regression replay of one frontier entry. With default
    arguments this reproduces the mined run exactly (``replaySeed`` is
    the sweep's sim seed): the returned result's score JSON digest must
    equal the entry's ``scoreDigest`` pin."""
    from ..testing.simulator import run_scenario
    if seed is None:
        seed = int(entry.get("replaySeed", entry.get("seed", 0)))
    return run_scenario(entry_spec(entry), seed=seed, ticks=ticks,
                        config_overrides=config_overrides)
