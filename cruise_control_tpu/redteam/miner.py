"""The red-team search loop: deterministic adversarial mining over the
(template, seed, perturbation) space (round 22).

The scenario library is hand-written — the system is only ever tested
against the failures somebody already imagined. This module points a
fuzzer-style mutate–score–keep loop at ``ScenarioScore``'s SLO floors
and makes the twin hunt for its own worst cases:

1. **Sample** a generation of candidate futures. Every choice is crc32-
   derived from the sweep seed (``_pick``/``zlib.crc32`` — the CCSA004
   discipline), so one sweep seed reproduces the whole search byte-for-
   byte.
2. **Screen** every candidate cheaply through the round-15 futures
   evaluator: advance each candidate's twin to its decision point
   (detection off) and solve all same-bucket decision models through
   ONE ``optimizations_megabatch`` program. The screen's
   ``balancedness_after`` ranks how stressed the topology is at the
   decision point. Perturbations that only re-time faults tie here
   (the screen never replays faults) — ties prefer the candidate with
   more heal-triggering events, then break byte-stably on the entry
   id, and the full-loop replay re-ranks the survivors honestly.
3. **Score** the worst survivors full-loop: ``run_scenario`` with
   detection + self-healing ON, scored by ``ScenarioScore`` whose
   margins and verdict strings render through ``utils/slo.py`` — mined
   verdicts are byte-identical to serving's.
4. **Keep** the K lowest-margin survivors as the frontier; the next
   generation mutates them (amplitude/phase/timing perturbations of
   the drift and event script, fault reordering, the late-fault
   squeeze) alongside fresh samples.

Budget discipline: the caller passes a ``clock`` callable and
``budget_s`` (or an eval budget); the miner NEVER reads the wall clock
itself (this module sits under CCSA004) and never silently truncates —
an exhausted budget ends the sweep with ``partial=True`` and the
reason recorded, the ``stage_partial`` rule bench enforces everywhere
else.
"""

from __future__ import annotations

import dataclasses
import json
import zlib
from typing import Callable, Mapping, Sequence

from ..futures.generator import (
    DEFAULT_TEMPLATES, PERTURBATION_KINDS, Perturbation, _pick,
    perturbed_future,
)
from ..utils.sensors import SENSORS
from ..utils.slo import scenario_margin

#: Entries with overall margin below this are "near-violations": close
#: enough to a floor that the forecaster blind-spot report asks whether
#: the predictive detector could have seen them coming. 0.1 = within
#: 10 points of the balancedness floor / 10% of the heal floor.
NEAR_MARGIN = 0.1

#: Mutation value alphabets, one per perturbation kind — small, named,
#: and crc32-indexed so a mutation is pure in (sweep seed, generation,
#: parent id, slot).
_MUTATION_VALUES: dict[str, tuple[float, ...]] = {
    "drift_amplitude": (0.5, 1.5, 2.0, 3.0),
    "drift_phase": (-20.0, -10.0, 10.0, 20.0),
    "event_timing": (-6.0, -3.0, 3.0, 6.0),
    "fault_reorder": (1.0, 2.0, 3.0),
    # The late-fault squeeze reaches deep into the horizon on purpose:
    # the healer closes small shifts easily, so the interesting values
    # are the ones that land a kill inside the window the heal can no
    # longer finish in.
    "fault_timing": (-8.0, 8.0, 16.0, 20.0),
}


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One point in the search space — and, serialized, one frontier
    entry's REPLAY RECIPE: ``perturbed_future(template, seed, ticks,
    perturbations)`` rebuilds the exact ScenarioSpec forever."""

    template: str
    seed: int
    ticks: int
    perturbations: tuple[Perturbation, ...] = ()

    def key_json(self) -> str:
        return json.dumps({
            "template": self.template, "seed": self.seed,
            "ticks": self.ticks,
            "perturbations": [p.as_dict() for p in self.perturbations],
        }, sort_keys=True)

    @property
    def entry_id(self) -> str:
        return f"m{zlib.crc32(self.key_json().encode()):08x}"

    def future(self):
        return perturbed_future(self.template, self.seed, self.ticks,
                                self.perturbations)

    @classmethod
    def from_dict(cls, d: Mapping) -> "Candidate":
        return cls(str(d["template"]), int(d["seed"]), int(d["ticks"]),
                   tuple(Perturbation.from_dict(p)
                         for p in d.get("perturbations", ())))


@dataclasses.dataclass(frozen=True)
class MinedEntry:
    """One scored frontier member: the candidate recipe plus the full-
    loop score pins (margin, verdicts, digests) its regression replay
    must reproduce byte-identically."""

    candidate: Candidate
    generation: int
    margin: float
    margins: Mapping[str, float]
    slo_violations: tuple[str, ...]
    score_digest: str
    assignment_digest: str
    balancedness_min: float | None
    blind_spot: Mapping | None = None

    @property
    def entry_id(self) -> str:
        return self.candidate.entry_id

    def as_dict(self) -> dict:
        return {
            "id": self.entry_id,
            "template": self.candidate.template,
            "seed": self.candidate.seed,
            "ticks": self.candidate.ticks,
            "perturbations": [p.as_dict()
                              for p in self.candidate.perturbations],
            "replaySeed": self.candidate.seed,
            "generation": self.generation,
            "margin": self.margin,
            "margins": dict(self.margins),
            "sloViolations": list(self.slo_violations),
            "scoreDigest": self.score_digest,
            "assignmentDigest": self.assignment_digest,
            "balancednessMin": self.balancedness_min,
            "blindSpot": dict(self.blind_spot)
            if self.blind_spot is not None else None,
        }


def params_from_config(config) -> dict:
    """The ``redteam.*`` knobs as ``mine()`` keyword arguments — the one
    translation both bench's stage and the tests use, so the config
    surface is the real parameterization and not decoration."""
    return {
        "population": config.get_int("redteam.population"),
        "generations": config.get_int("redteam.generations"),
        "survivors": config.get_int("redteam.survivors"),
        "frontier_size": config.get_int("redteam.frontier.size"),
        "ticks": config.get_int("redteam.ticks"),
        "eval_budget": config.get_int("redteam.eval.budget"),
    }


def _fresh(sweep_seed: int, gen: int, slot: int, ticks: int,
           templates: Sequence[str]) -> Candidate:
    tag = f"g{gen}:fresh:{slot}"
    template = templates[_pick(sweep_seed, f"{tag}:tmpl", len(templates))]
    seed = zlib.crc32(f"{sweep_seed}:{tag}:seed".encode()) % 100_000
    return Candidate(template, seed, ticks)


def _mutate(parent: Candidate, sweep_seed: int, gen: int,
            slot: int) -> Candidate:
    tag = f"g{gen}:mut:{parent.entry_id}:{slot}"
    kind = PERTURBATION_KINDS[
        _pick(sweep_seed, f"{tag}:kind", len(PERTURBATION_KINDS))]
    values = _MUTATION_VALUES[kind]
    value = values[_pick(sweep_seed, f"{tag}:value", len(values))]
    return dataclasses.replace(
        parent,
        perturbations=parent.perturbations + (Perturbation(kind, value),))


def _screen(candidates: Sequence[Candidate], optimizer, width: int,
            config_overrides: Mapping | None) -> list[tuple]:
    """Cheap generation screen: one megabatched decision solve per
    candidate, worst topology first. Returns ``(ranked, optimizer)``:
    ``ranked`` is ``(screen_score, entry_id, candidate)`` sorted
    ascending — a candidate whose solve ERRORS screens worst of all
    (-1.0): a future the optimizer cannot even answer is exactly what a
    red team wants a closer look at — and ``optimizer`` is the (lazily
    created) GoalOptimizer the sweep reuses so later generations hit
    the same compiled programs.

    The screen never replays faults, so every fault story ties on
    ``balancedness_after``. Among ties the candidate carrying MORE
    heal-triggering events ranks first (then the entry id, byte-
    stably): the fuzzer prior that a kill-bearing future deserves the
    full-loop replay over a calm one with the same decision topology —
    without it, fault futures lose the tie-break lottery and the whole
    unhealed-fault family goes unscored."""
    from ..analyzer.optimizer import GoalOptimizer
    from ..futures.evaluator import (
        FutureSpec, evaluate_prepared, prepare_sampled,
    )
    from ..testing.simulator import HEAL_TRIGGERING
    prepared = []
    faults = {}
    for c in candidates:
        f = c.future()
        faults[c.entry_id] = sum(1 for e in f.spec.events
                                 if e.kind in HEAL_TRIGGERING)
        prepared.append(prepare_sampled(
            f, c.ticks, optimizer=optimizer,
            config_overrides=config_overrides,
            fspec=FutureSpec(c.template, c.seed, c.ticks)))
    if optimizer is None:
        optimizer = GoalOptimizer(prepared[0].config)
    results = evaluate_prepared(prepared, optimizer, width=width,
                                batched=True)
    ranked = []
    for c, r in zip(candidates, results):
        score = -1.0 if r.error else float(r.balancedness_after or 0.0)
        ranked.append((score, c.entry_id, c))
    ranked.sort(key=lambda t: (t[0], -faults[t[1]], t[1]))
    return ranked, optimizer


def _score_full_loop(cand: Candidate, generation: int,
                     config_overrides: Mapping | None) -> MinedEntry:
    """The survivor's honest evaluation: full loop (detection + self-
    healing ON), scored through the shared SLO renderer."""
    from ..testing.simulator import run_scenario
    result = run_scenario(cand.future().spec, seed=cand.seed,
                          config_overrides=config_overrides)
    margins = result.score.slo_margins()
    score_digest = f"{zlib.crc32(result.score.to_json().encode()):08x}"
    bal = result.score.balancedness
    return MinedEntry(
        candidate=cand, generation=generation,
        margin=round(scenario_margin(margins), 6),
        margins=margins,
        slo_violations=tuple(result.score.slo_violations()),
        score_digest=score_digest,
        assignment_digest=result.assignment_digest,
        balancedness_min=min(bal) if bal else None)


def mine(sweep_seed: int = 0, *,
         templates: Sequence[str] | None = None,
         population: int = 12, generations: int = 4, survivors: int = 4,
         frontier_size: int = 8, ticks: int = 24, eval_budget: int = 200,
         width: int = 8, optimizer=None,
         config_overrides: Mapping | None = None,
         library: Mapping[str, float] | None = None,
         budget_s: float | None = None,
         clock: Callable[[], float] | None = None,
         tag_blind_spots: bool = True) -> dict:
    """One mining sweep → the frontier dict (``frontier.frontier_json``
    serializes it byte-identically at one sweep seed).

    ``clock``/``budget_s`` are the wall budget seam: the CALLER owns the
    clock (bench passes ``time.monotonic``; deterministic tests pass
    nothing) — this module never reads wall time. ``eval_budget``
    bounds total candidate evaluations (screen solves + full-loop
    replays). Either budget expiring ends the sweep with
    ``partial=True`` + the reason — never a silent cap. ``library``
    is the canonical library's margin map (``library_margins``),
    carried into the result so "did the miner beat every hand-written
    scenario?" is answered inside the artifact."""
    templates = tuple(templates or DEFAULT_TEMPLATES)
    start = clock() if clock is not None else None

    def wall_exceeded() -> bool:
        return (clock is not None and budget_s is not None
                and clock() - start > budget_s)

    frontier: dict[str, MinedEntry] = {}
    seen: set[str] = set()
    evals = replays = 0
    gens_run = 0
    partial_reason: str | None = None

    for gen in range(generations):
        if wall_exceeded():
            partial_reason = f"wall budget ({budget_s}s) before gen {gen}"
            break
        if evals + replays >= eval_budget:
            partial_reason = (f"eval budget ({eval_budget}) before "
                              f"gen {gen}")
            break
        # Build the generation: mutations of the current frontier
        # (worst first, round-robin) fill half the population, fresh
        # crc32-derived samples the rest. Generation 0 is all fresh.
        cands: list[Candidate] = []
        parents = sorted(frontier.values(),
                         key=lambda e: (e.margin, e.entry_id))
        slot = 0
        while parents and len(cands) < population // 2:
            parent = parents[slot % len(parents)]
            cand = _mutate(parent.candidate, sweep_seed, gen, slot)
            slot += 1
            if cand.entry_id in seen:
                continue
            seen.add(cand.entry_id)
            cands.append(cand)
            if slot > population * 4:    # all mutations already seen
                break
        slot = 0
        while len(cands) < population:
            cand = _fresh(sweep_seed, gen, slot, ticks, templates)
            slot += 1
            if cand.entry_id in seen:
                continue
            seen.add(cand.entry_id)
            cands.append(cand)
            if slot > population * 4:
                break
        if not cands:
            break
        remaining = max(0, eval_budget - evals - replays)
        if len(cands) > remaining:
            cands = cands[:remaining]
            partial_reason = (f"eval budget ({eval_budget}) truncated "
                              f"gen {gen} to {len(cands)} candidates")
        ranked, optimizer = _screen(cands, optimizer, width,
                                    config_overrides)
        evals += len(cands)
        SENSORS.count("redteam_evals", len(cands))
        gens_run = gen + 1
        for _score, _eid, cand in ranked[:survivors]:
            if wall_exceeded():
                partial_reason = (f"wall budget ({budget_s}s) during "
                                  f"gen {gen} replays")
                break
            if evals + replays >= eval_budget:
                partial_reason = (f"eval budget ({eval_budget}) during "
                                  f"gen {gen} replays")
                break
            entry = _score_full_loop(cand, gen, config_overrides)
            replays += 1
            SENSORS.count("redteam_replays")
            frontier[entry.entry_id] = entry
        worst = sorted(frontier.values(),
                       key=lambda e: (e.margin, e.entry_id))
        frontier = {e.entry_id: e for e in worst[:frontier_size]}
        if partial_reason:
            break

    entries = sorted(frontier.values(), key=lambda e: (e.margin,
                                                       e.entry_id))
    blind_spots = 0
    out_entries = []
    for e in entries:
        blind = None
        if tag_blind_spots:
            from .blindspot import entry_blind_spot
            blind = entry_blind_spot(e.candidate.future().spec, e.margin)
            if blind["tagged"]:
                blind_spots += 1
        out_entries.append(dataclasses.replace(e, blind_spot=blind)
                           .as_dict())
    if entries:
        SENSORS.gauge("redteam_frontier_margin_min", entries[0].margin)
    SENSORS.count("redteam_blind_spots", blind_spots)

    lib = None
    found_below_library = None
    if library is not None:
        lib_min = min(library.values()) if library else None
        lib = {"margins": dict(library), "minMargin": lib_min}
        if lib_min is not None:
            found_below_library = sum(
                1 for e in entries if e.margin < lib_min)
    return {
        "version": 1,
        "sweepSeed": sweep_seed,
        "templates": list(templates),
        "ticks": ticks,
        "population": population,
        "generationsRequested": generations,
        "generationsRun": gens_run,
        "evals": evals,
        "replays": replays,
        "partial": partial_reason is not None,
        "partialReason": partial_reason,
        "library": lib,
        "foundBelowLibrary": found_below_library,
        "blindSpotCount": blind_spots,
        "frontier": out_entries,
    }


def library_margins(seed: int = 0) -> dict[str, float]:
    """The canonical library's overall margins, full-loop at their
    native horizons — the bar a mined scenario must get UNDER to count
    as a discovery (acceptance: margin below the library's minimum).
    Expensive (it replays every canonical scenario); run offline to
    stamp the committed frontier, not inside the CI stage budget."""
    from ..testing.simulator import CANONICAL_SCENARIOS, run_scenario
    out = {}
    for name, spec in sorted(CANONICAL_SCENARIOS.items()):
        result = run_scenario(spec, seed=seed)
        out[name] = round(scenario_margin(result.score.slo_margins()), 6)
    return out
