"""Adversarial scenario mining (round 22): a red-team search engine
over the futures evaluator, with a persistent worst-case regression
frontier. See ``miner.py`` for the search loop, ``frontier.py`` for the
replayable persistence format, ``blindspot.py`` for the forecaster
blind-spot tagging."""

from .blindspot import entry_blind_spot, forecast_miss, global_factor_series
from .frontier import (
    DEFAULT_FRONTIER_PATH, entry_candidate, entry_spec, frontier_json,
    load_frontier, replay_entry, save_frontier,
)
from .miner import Candidate, MinedEntry, library_margins, mine, params_from_config

__all__ = [
    "Candidate", "MinedEntry", "mine", "library_margins",
    "params_from_config",
    "DEFAULT_FRONTIER_PATH", "frontier_json", "load_frontier",
    "save_frontier", "entry_candidate", "entry_spec", "replay_entry",
    "global_factor_series", "forecast_miss", "entry_blind_spot",
]
