"""Seeded randomized scenario generator: candidate *futures* of a cluster.

Round 11's canonical library is six hand-written scenarios; this module
grows the scenario-diversity axis ROADMAP item 5 names — heterogeneous
capacities, cascading broker failures, partition-churn storms,
maintenance plans, forecast-percentile load ramps — as TEMPLATES whose
concrete parameters (which broker dies, how hot the ramp runs, when the
churn lands) are sampled from a seed.

Determinism contract (the same one ``testing/simulator.py`` carries, and
the reason this module sits under CCSA004): every sampler is a pure
function of ``(template, seed)`` via crc32 derivation — no wall clock, no
``random`` module, no ``hash()`` — so a sampled scenario is byte-for-byte
reproducible from its ``(template, seed)`` pair, a ``?what_if=
random:<template>:<seed>`` replay returns the same score JSON every
time, and the CI matrix can pin sampled rows like canonical ones.

Two consumers with two views of one sample:

- ``sample_scenario(template, seed)`` → a full ``ScenarioSpec`` for the
  digital twin's COMPLETE loop (detection + self-healing on): the
  ``?what_if=random:...`` replay path and the CI SCENARIO_MATRIX rows.
- ``sample_future(template, seed, ticks)`` → a ``SampledFuture``: the
  load-shaping events rescaled into the evaluator's (short) advance
  horizon plus the DECISION-POINT mutations (brokers to mark dead/new at
  the batched solve) — ``futures/evaluator.py``'s input. Fault and
  maintenance content lives in the decision mutations there, because the
  evaluator advances its twins with detection off and asks "what would
  the solver propose if this future arrived now?".

All templates share one cluster geometry (``BASE_SPEC``) so every
sampled future pads to the SAME bucket shape and the evaluator can stack
dozens of them through one compiled megabatch program.
"""

from __future__ import annotations

import dataclasses
import zlib
from typing import Callable

from ..testing.simulator import (
    DriftSpec, ScenarioEvent, ScenarioSpec, _hash01,
)

#: Shared geometry: every template (and the "present" baseline) uses this
#: spec, so all sampled futures share one padded bucket shape — the
#: megabatch grouping precondition. Topic count is FIXED (churn is
#: partition-expansion only) because ``num_topics`` is a static solver
#: argument: creating topics would split futures into separate programs.
BASE_SPEC = ScenarioSpec(
    name="present",
    description="The cluster as it is: no injected events, no drift.",
    num_brokers=6, num_topics=4, partitions_per_topic=12, rf=2,
    num_racks=3, ticks=60, tick_s=60.0,
    # The futures goal chain adds a load-distribution goal to the twin's
    # churn-sensitive default so load-shaped futures (ramps, hotspots,
    # capacity skew) actually rank differently; shared across templates
    # so the resolved chain is one grouping key.
    config_overrides={
        "goals": [
            "cruise_control_tpu.analyzer.goals.RackAwareGoal",
            "cruise_control_tpu.analyzer.goals.ReplicaCapacityGoal",
            "cruise_control_tpu.analyzer.goals.DiskCapacityGoal",
            "cruise_control_tpu.analyzer.goals."
            "NetworkInboundUsageDistributionGoal",
            "cruise_control_tpu.analyzer.goals.ReplicaDistributionGoal",
        ],
    })

#: Event kinds the EVALUATOR replays during its advance phase (they shape
#: the load/topology the decision solve sees). Fault/maintenance kinds are
#: decision-point content there — the full-loop what-if replay keeps them
#: as scripted events.
ADVANCE_KINDS = ("set_load", "hotspot", "clear_hotspot",
                 "expand_partitions")


def _pick(seed: int, tag: str, n: int) -> int:
    """Deterministic choice in [0, n) (PYTHONHASHSEED-stable)."""
    return zlib.crc32(f"{seed}:{tag}".encode()) % max(1, n)


@dataclasses.dataclass(frozen=True)
class SampledFuture:
    """One sampled candidate future of the cluster.

    ``spec`` is the full-loop scenario (what-if replay / CI matrix);
    ``remove_brokers``/``add_brokers`` are the decision-point mutations
    the batched evaluator applies to the model before the solve (marked
    DEAD/NEW exactly like the facade's remove/add operations, with the
    removed brokers excluded from replica moves and leadership — the
    per-future exclusion options that ride the megabatch mask
    assembler)."""

    template: str
    seed: int
    spec: ScenarioSpec
    remove_brokers: tuple[int, ...] = ()
    add_brokers: tuple[int, ...] = ()

    @property
    def future_id(self) -> str:
        return f"{self.template}:{self.seed}"

    def _rescaled_events(self, ticks: int,
                         kinds: tuple[str, ...] | None = None,
                         ) -> tuple[ScenarioEvent, ...]:
        """Event times are proportional positions on the spec's horizon:
        rescale them into a horizon of ``ticks`` (optionally filtered to
        ``kinds``) so a shorter run sees the same STORY, compressed.
        Pure in (self, ticks, kinds)."""
        out = []
        for e in self.spec.events:
            if kinds is not None and e.kind not in kinds:
                continue
            t = min(ticks - 1, max(0, round(e.tick * ticks
                                            / max(1, self.spec.ticks))))
            out.append(ScenarioEvent(t, e.kind, e.params))
        return tuple(sorted(out, key=lambda e: (e.tick, e.kind,
                                                sorted(e.params.items()))))

    def advance_events(self, ticks: int) -> tuple[ScenarioEvent, ...]:
        """The load-shaping subset of the sampled events, rescaled into
        the evaluator's advance horizon."""
        return self._rescaled_events(ticks, ADVANCE_KINDS)

    def replay_spec(self, ticks: int) -> ScenarioSpec:
        """The FULL-loop spec compressed into ``ticks`` — every event
        (faults and maintenance included) rescaled proportionally, so a
        short serial replay evaluates the same story the evaluator's
        advance horizon sees (the bench's apples-to-apples serial
        baseline; plain truncation would silently drop late events)."""
        return dataclasses.replace(self.spec, ticks=int(ticks),
                                   events=self._rescaled_events(ticks))


def _named(template: str, seed: int, base: ScenarioSpec,
           description: str, **changes) -> ScenarioSpec:
    overrides = {**dict(base.config_overrides),
                 **changes.pop("config_overrides", {})}
    return dataclasses.replace(
        base, name=f"random:{template}:{seed}",
        description=description, config_overrides=overrides, **changes)


# ---------------------------------------------------------------------------
# Templates
# ---------------------------------------------------------------------------

def _load_ramp(seed: int, base: ScenarioSpec = BASE_SPEC,
               ) -> SampledFuture:
    """Forecast-percentile load ramp: the cluster's next N hours under a
    demand forecast — which percentile arrives, when the ramp lands, and
    how hard the diurnal swing rides on top are all sampled."""
    u = _hash01(seed, "ramp", "pct")
    pct, factor = ("p50", 1.25) if u < 1 / 3 else \
        ("p90", 1.7) if u < 2 / 3 else ("p99", 2.4)
    amp = round(0.15 + 0.35 * _hash01(seed, "ramp", "amp"), 3)
    start = 6 + _pick(seed, "ramp:start", 18)
    hot_topic = f"t{_pick(seed, 'ramp:topic', base.num_topics)}"
    hot = round(1.5 + 2.0 * _hash01(seed, "ramp", "hot"), 2)
    events = (
        ScenarioEvent(start, "set_load", {"factor": factor}),
        ScenarioEvent(start + 8, "hotspot",
                      {"topic": hot_topic, "factor": hot}),
    )
    return SampledFuture("load_ramp", seed, _named(
        "load_ramp", seed, base,
        f"Forecast {pct} load ramp (x{factor}) from tick {start} with a "
        f"x{hot} hotspot on {hot_topic}, diurnal amplitude {amp}.",
        drift=DriftSpec(amplitude=amp, period_ticks=40), events=events,
        config_overrides={"scenario.slo.balancedness.min": 60.0}))


def _capacity_skew(seed: int, base: ScenarioSpec = BASE_SPEC,
                   ) -> SampledFuture:
    """Heterogeneous capacities: half the fleet scaled by a sampled
    factor (a mixed-generation hardware future), with a sampled hotspot
    so placement by capacity share actually matters."""
    skew = round(1.5 + 1.5 * _hash01(seed, "skew", "factor"), 2)
    hot_topic = f"t{_pick(seed, 'skew:topic', base.num_topics)}"
    hot = round(1.5 + 1.5 * _hash01(seed, "skew", "hot"), 2)
    start = 5 + _pick(seed, "skew:start", 15)
    events = (
        ScenarioEvent(start, "hotspot", {"topic": hot_topic,
                                         "factor": hot}),
    )
    return SampledFuture("capacity_skew", seed, _named(
        "capacity_skew", seed, base,
        f"Brokers 0-{base.num_brokers // 2 - 1} at x{skew} capacity "
        f"(heterogeneous fleet) under a x{hot} hotspot on {hot_topic}.",
        capacity_skew=skew, events=events,
        config_overrides={"scenario.slo.balancedness.min": 60.0}))


def _cascading_failures(seed: int, base: ScenarioSpec = BASE_SPEC,
                        ) -> SampledFuture:
    """Cascading broker/AZ failures: a first broker dies, then a second
    in a DIFFERENT rack a few ticks later (the cross-AZ cascade), both
    reviving late in the replay. The evaluator's decision point sits
    mid-outage: both victims marked DEAD at the solve, excluded from
    replica moves and leadership."""
    b = base.num_brokers
    first = _pick(seed, "cascade:first", b)
    # A different rack (racks are broker % num_racks): step by one so the
    # cascade always crosses an AZ boundary.
    second = (first + 1) % b
    t1 = 8 + _pick(seed, "cascade:t1", 10)
    gap = 3 + _pick(seed, "cascade:gap", 6)
    revive = base.ticks - 18
    events = (
        ScenarioEvent(t1, "kill_broker", {"broker": first}),
        ScenarioEvent(t1 + gap, "kill_broker", {"broker": second}),
        ScenarioEvent(revive, "revive_broker", {"broker": first}),
        ScenarioEvent(revive, "revive_broker", {"broker": second}),
    )
    return SampledFuture(
        "cascading_failures", seed, _named(
            "cascading_failures", seed, base,
            f"Broker {first} dies at tick {t1}, broker {second} (next "
            f"rack) follows {gap} ticks later; both revive at "
            f"tick {revive}.",
            events=events,
            # Sub-horizon removal history (the multi_az_failure lesson):
            # healed-then-revived brokers must become placement targets
            # again before the replay ends.
            config_overrides={
                "removal.history.retention.time.ms": 1_200_000,
                "scenario.slo.balancedness.min": 60.0}),
        remove_brokers=(first, second))


def _churn_storm(seed: int, base: ScenarioSpec = BASE_SPEC,
                 ) -> SampledFuture:
    """Partition-expansion churn storm: existing topics grow in sampled
    bursts (topic COUNT stays fixed so every churn future shares the
    batch's static topic axis; total partitions stay within ONE
    geometric 128-grid step of the base so the storm crosses at most
    one padded-shape boundary)."""
    from ..fleet.bucketing import geometric_round_up
    events = []
    grown: dict[str, int] = {}
    # At most double the base partition count, additionally capped at
    # the next 128-based geometric grid point strictly above the base
    # total (BASE_SPEC: 48 -> min(48, 128-48) = 48, digests unchanged).
    # A LIVE base near or past a bucket boundary must not grow the twin
    # across several padded shapes: each crossing recompiles mid-replay
    # and splits the decision solve out of the batch's shared shape.
    total = base.num_topics * base.partitions_per_topic
    bound = geometric_round_up(total + 1, 128, 2.0)
    budget = budget0 = min(total, max(0, bound - total))
    cadence = 5 + _pick(seed, "churn:cadence", 5)
    for tick in range(cadence, base.ticks - 5, cadence):
        if budget <= 0:
            break
        topic = f"t{_pick(seed, f'churn:topic:{tick}', base.num_topics)}"
        step = min(budget, 4 + 4 * _pick(seed, f"churn:step:{tick}", 2))
        grown[topic] = grown.get(topic, base.partitions_per_topic) + step
        budget -= step
        events.append(ScenarioEvent(tick, "expand_partitions",
                                    {"topic": topic, "to": grown[topic]}))
    return SampledFuture("churn_storm", seed, _named(
        "churn_storm", seed, base,
        f"Partition-expansion bursts every {cadence} ticks across "
        f"{len(grown)} topics "
        f"(+{budget0 - budget} partitions total).",
        events=tuple(events),
        config_overrides={"scenario.slo.balancedness.min": 60.0}))


def _maintenance_plan(seed: int, base: ScenarioSpec = BASE_SPEC,
                      ) -> SampledFuture:
    """Maintenance plan: one sampled broker drained (REMOVE_BROKER plan)
    and re-added later in the replay. At the evaluator's decision point
    the drain is in force: the broker is marked DEAD and excluded, the
    solve prices evacuating it."""
    victim = _pick(seed, "maint:broker", base.num_brokers)
    t1 = 8 + _pick(seed, "maint:t1", 12)
    t2 = base.ticks - 15
    events = (
        ScenarioEvent(t1, "maintenance",
                      {"plan": "REMOVE_BROKER", "brokers": [victim]}),
        ScenarioEvent(t2, "maintenance",
                      {"plan": "ADD_BROKER", "brokers": [victim]}),
    )
    return SampledFuture(
        "maintenance_plan", seed, _named(
            "maintenance_plan", seed, base,
            f"Drain broker {victim} at tick {t1} (maintenance plan), "
            f"re-add at tick {t2}.",
            events=events,
            config_overrides={"scenario.slo.balancedness.min": 60.0}),
        remove_brokers=(victim,))


def _forecast_horizon(seed: int, base: ScenarioSpec = BASE_SPEC,
                      ) -> SampledFuture:
    """The forecaster's own projection as a future (round 19, the
    natural sixth template): "what would the solver propose against the
    loads the forecaster says are coming?". LIVE-ONLY — the evaluator
    builds this future directly from the serving cluster's model with
    its load planes replaced by the engine's projection at a SAMPLED
    band position (lower / mean / upper confidence band, the
    percentile axis other templates fake with synthetic factors), so it
    is meaningless without the live seam and is excluded from default
    template expansion (``requires_live``). The spec here only carries
    the shared goal chain + naming for ranking/replay bookkeeping."""
    return SampledFuture("forecast_horizon", seed, _named(
        "forecast_horizon", seed, base,
        f"The live cluster under its own forecast at band position "
        f"{band_position(seed):+d}σ."))


def band_position(seed: int) -> int:
    """Sampled confidence-band position for a forecast_horizon future:
    -1 (lower band), 0 (mean), +1 (upper band) — pure in seed."""
    return _pick(seed, "fh:band", 3) - 1


@dataclasses.dataclass(frozen=True)
class FutureTemplate:
    name: str
    description: str
    sample: Callable[[int], SampledFuture]
    #: True = only meaningful with the live-cluster seam (evaluator
    #: LiveSeed): excluded from default template expansion so pinned
    #: default plans (bench ranked_order, the CI matrix) are unchanged.
    requires_live: bool = False


FUTURE_TEMPLATES: dict[str, FutureTemplate] = {t.name: t for t in (
    FutureTemplate("load_ramp",
                   "Forecast-percentile load ramp + hotspot under drift",
                   _load_ramp),
    FutureTemplate("capacity_skew",
                   "Heterogeneous broker capacities (mixed generations)",
                   _capacity_skew),
    FutureTemplate("cascading_failures",
                   "Cross-AZ cascading broker failures, revived late",
                   _cascading_failures),
    FutureTemplate("churn_storm",
                   "Seeded partition-expansion bursts (fixed topic axis)",
                   _churn_storm),
    FutureTemplate("maintenance_plan",
                   "Broker drain + re-add maintenance plan",
                   _maintenance_plan),
    FutureTemplate("forecast_horizon",
                   "The live cluster under its own projected loads "
                   "(round 19; live seam only)",
                   _forecast_horizon, requires_live=True),
)}

#: Default expansion set (an empty templates request): the synthetic
#: templates only — requires_live ones must be asked for by name.
DEFAULT_TEMPLATES = tuple(sorted(
    n for n, t in FUTURE_TEMPLATES.items() if not t.requires_live))


def _unknown(template: str) -> ValueError:
    return ValueError(
        f"unknown futures template {template!r}; expected one of "
        f"{', '.join(sorted(FUTURE_TEMPLATES))}")


def sample_future(template: str, seed: int,
                  ticks: int | None = None,
                  base: ScenarioSpec | None = None) -> SampledFuture:
    """Sample one candidate future — pure in ``(template, seed)`` (and
    ``base`` when the live seam supplies one: same seed + same live
    geometry ⇒ the same future). ``ticks`` re-times the spec's replay
    horizon (the advance-phase event positions rescale with it via
    ``advance_events``); ``base`` swaps the shared BASE_SPEC geometry
    for the LIVE cluster's (futures of THIS cluster, ROADMAP 5b)."""
    t = FUTURE_TEMPLATES.get(template)
    if t is None:
        raise _unknown(template)
    sampled = t.sample(int(seed)) if base is None \
        else t.sample(int(seed), base)
    if ticks is not None:
        sampled = dataclasses.replace(
            sampled, spec=dataclasses.replace(sampled.spec,
                                              ticks=int(ticks)))
    return sampled


def sample_scenario(template: str, seed: int) -> ScenarioSpec:
    """The full-loop ``ScenarioSpec`` view of a sample (the
    ``?what_if=random:<template>:<seed>`` replay and the CI matrix's
    generator-sampled rows)."""
    return sample_future(template, seed).spec


def present_future() -> SampledFuture:
    """The baseline slot: the cluster exactly as it is. Ranked answers
    report score DELTAS against this future's solve."""
    return SampledFuture("present", 0, BASE_SPEC)


# ---------------------------------------------------------------------------
# Perturbations (round 22): the red-team miner's mutation alphabet
# ---------------------------------------------------------------------------

#: Mutation kinds the red-team miner composes. Each is a PURE spec
#: transform — ``(spec, perturbation) -> spec`` with no sampling inside —
#: so a frontier entry (template, seed, ticks, perturbations) rebuilds a
#: byte-identical ScenarioSpec forever.
PERTURBATION_KINDS = ("drift_amplitude", "drift_phase", "event_timing",
                      "fault_reorder", "fault_timing")

#: Fault-event kinds the ``fault_reorder`` perturbation permutes (the
#: heal-triggering set — timing order between correlated faults is
#: exactly what a cascade's severity hangs on).
_FAULT_KINDS = ("kill_broker", "kill_logdir")


@dataclasses.dataclass(frozen=True)
class Perturbation:
    """One serializable mutation of a sampled spec.

    - ``drift_amplitude``: multiply the diurnal amplitude by ``value``
      (a zero-amplitude spec is seeded at 0.2 first so the perturbation
      has something to scale), clamped below 1.0.
    - ``drift_phase``: shift the drift wave by ``value`` ticks
      (``DriftSpec.phase_ticks``) — the scenario starts elsewhere on
      the wave, e.g. at the crest the moment a broker dies.
    - ``event_timing``: shift every scripted event by ``round(value)``
      ticks, clamped into the horizon (relative order preserved away
      from the clamp edges).
    - ``fault_reorder``: rotate the tick assignments among the
      heal-triggering events by ``round(value)`` positions — the
      cascade arrives in a different order at the same instants.
    - ``fault_timing``: shift ONLY the heal-triggering events by
      ``round(value)`` ticks (load/maintenance script untouched),
      clamped into the horizon — the late-fault squeeze: how close to
      the end of the SLO window can a kill land and still heal inside
      it? Positive values past the healer's closing speed are exactly
      the unhealed-fault violations the miner hunts.
    """

    kind: str
    value: float = 0.0

    def as_dict(self) -> dict:
        return {"kind": self.kind, "value": self.value}

    @classmethod
    def from_dict(cls, d) -> "Perturbation":
        return cls(str(d["kind"]), float(d.get("value", 0.0)))


def _sorted_events(events) -> tuple[ScenarioEvent, ...]:
    return tuple(sorted(events, key=lambda e: (e.tick, e.kind,
                                               sorted(e.params.items()))))


def apply_perturbations(spec: ScenarioSpec,
                        perturbations) -> ScenarioSpec:
    """Apply a perturbation sequence to a spec — pure, order-sensitive,
    and total (an unknown kind raises instead of silently no-opping, so
    a frontier file from a future alphabet cannot half-replay)."""
    for p in perturbations:
        if p.kind == "drift_amplitude":
            base_amp = spec.drift.amplitude or 0.2
            amp = round(min(0.95, max(0.0, base_amp * float(p.value))), 4)
            spec = dataclasses.replace(
                spec, drift=dataclasses.replace(spec.drift, amplitude=amp))
        elif p.kind == "drift_phase":
            phase = round(spec.drift.phase_ticks + float(p.value), 4)
            spec = dataclasses.replace(
                spec, drift=dataclasses.replace(spec.drift,
                                                phase_ticks=phase))
        elif p.kind == "event_timing":
            delta = int(round(float(p.value)))
            moved = [ScenarioEvent(min(spec.ticks - 1, max(0, e.tick + delta)),
                                   e.kind, e.params)
                     for e in spec.events]
            spec = dataclasses.replace(spec, events=_sorted_events(moved))
        elif p.kind == "fault_timing":
            delta = int(round(float(p.value)))
            moved = [ScenarioEvent(min(spec.ticks - 1, max(0, e.tick + delta)),
                                   e.kind, e.params)
                     if e.kind in _FAULT_KINDS else e
                     for e in spec.events]
            spec = dataclasses.replace(spec, events=_sorted_events(moved))
        elif p.kind == "fault_reorder":
            faults = [e for e in spec.events if e.kind in _FAULT_KINDS]
            if len(faults) > 1:
                rot = int(round(float(p.value))) % len(faults)
                ticks = [e.tick for e in faults]
                rotated = {id(e): ticks[(i + rot) % len(faults)]
                           for i, e in enumerate(faults)}
                moved = [ScenarioEvent(rotated[id(e)], e.kind, e.params)
                         if id(e) in rotated else e
                         for e in spec.events]
                spec = dataclasses.replace(spec,
                                           events=_sorted_events(moved))
        else:
            raise ValueError(
                f"unknown perturbation kind {p.kind!r}; expected one of "
                f"{', '.join(PERTURBATION_KINDS)}")
    return spec


def perturbed_future(template: str, seed: int, ticks: int,
                     perturbations,
                     base: ScenarioSpec | None = None) -> SampledFuture:
    """The miner's candidate constructor: sample ``(template, seed)``,
    compress the full story into ``ticks`` (``replay_spec`` — faults
    included), then apply the perturbation sequence. Pure in all
    arguments, so a frontier entry IS this call's argument list."""
    sampled = sample_future(template, seed, base=base)
    spec = sampled.replay_spec(int(ticks))
    spec = apply_perturbations(spec, tuple(perturbations))
    return dataclasses.replace(sampled, spec=spec)
