"""Batched futures evaluator: dozens of candidate futures, one solve.

Round 11 answers "what if?" by replaying ONE scenario serially on a twin
(every tick pays its own detector/solver cycle). This module turns
scenario evaluation into a batched device workload (ROADMAP item 5):

1. **Advance** — each candidate future gets its own digital twin
   (``testing/simulator.py`` with anomaly detection off: the advance
   phase is pure simulation, no solver work) and runs to its decision
   point: load-shaping events applied, drift sampled, the monitor's
   windows filled on the injected clock.
2. **Decide** — each future's decision-point mutations (brokers dying or
   draining in that future) are marked on its cluster model exactly like
   the facade's remove/add operations, with matching per-future
   exclusion options.
3. **Solve** — all same-bucket futures stack through
   ``GoalOptimizer.optimizations_megabatch`` (per-item options ride the
   batched mask assembler; inert pad slots mean ONE compiled program per
   bucket shape serves any occupancy) instead of solving serially.
4. **Rank** — per-future ``ScenarioScore``-style dicts, ranked best
   balancedness first with byte-stable tie-breaks, each carrying score
   deltas vs the ``present`` baseline future.

Determinism contract (CCSA004 scope): the response body contains NO
wall-clock-derived values — same ``(templates, seed, ticks)`` request ⇒
byte-identical ranked JSON, batched or serial, at any occupancy. Wall
time goes to sensors/spans only.

``FuturesPayload`` adapts a COMPARE_FUTURES request to the fleet's
``MegabatchRunner`` payload protocol, so a futures request queued behind
(or beside) paced precomputes coalesces into the same scheduler turn —
the first workload where batch occupancy is driven by user traffic
rather than fleet size.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Mapping, Sequence

import numpy as np

PRESENT = "present"

#: Twin overrides for the advance phase: detection/self-healing OFF (the
#: decision solve is the only solver work a future costs) and no
#: proposal probes (there is no serving path inside an advance twin).
_ADVANCE_OVERRIDES = {
    "self.healing.enabled": False,
    "anomaly.detection.interval.ms": 10 ** 12,
    "metric.anomaly.detection.interval.ms": 10 ** 12,
    "scenario.proposal.probe.ticks": 0,
}

#: The monitor needs its window count filled before a model build; the
#: twin fills one window per tick.
_MIN_TICKS = 4


@dataclasses.dataclass(frozen=True)
class FutureSpec:
    """One requested future: which template, which seed, how far to
    advance before the decision solve."""

    template: str
    seed: int = 0
    ticks: int = 12

    @property
    def future_id(self) -> str:
        if self.template == PRESENT:
            return PRESENT
        return f"{self.template}:{self.seed}"


def plan_futures(templates: Sequence[str], num_futures: int, seed: int,
                 ticks: int) -> list[FutureSpec]:
    """Expand a request into concrete (template, seed) pairs: templates
    round-robin, seeds advance once per full cycle — every row of the
    answer is independently replayable via
    ``?what_if=random:<template>:<seed>``. Duplicate template names are
    dropped (order-preserving): repeating a template cannot mean
    anything but re-solving the identical future, and colliding
    future ids would corrupt the ranked answer. Default expansion (an
    empty request) covers the SYNTHETIC templates only —
    ``requires_live`` ones (forecast_horizon) must be named, so pinned
    default plans (bench ranked_order, the CI matrix) never change
    under a new live-only template."""
    from .generator import _unknown, DEFAULT_TEMPLATES, FUTURE_TEMPLATES
    templates = list(dict.fromkeys(templates)) or list(DEFAULT_TEMPLATES)
    for t in templates:
        if t not in FUTURE_TEMPLATES:
            raise _unknown(t)
    ticks = max(_MIN_TICKS, int(ticks))
    return [FutureSpec(templates[i % len(templates)],
                       seed + i // len(templates), ticks)
            for i in range(max(1, int(num_futures)))]


@dataclasses.dataclass
class LiveSeed:
    """The live-cluster seam (ROADMAP 5b): the serving facade's model,
    config, and forecast engine, plus a ``base`` ScenarioSpec carrying
    the LIVE geometry — candidate futures sampled against it are
    futures of THIS cluster, not of the reference twin."""

    state: Any
    meta: Any
    config: Any
    engine: Any = None     # ForecastEngine | None
    base: Any = None       # ScenarioSpec with live geometry


def live_base_spec(state, meta):
    """BASE_SPEC with the live cluster's geometry swapped in (brokers,
    racks, RF, topic/partition counts); the shared futures goal chain
    and replay horizon are kept so sampled futures stay comparable."""
    import math as _math

    from .generator import BASE_SPEC
    num_topics = max(1, len(meta.topic_names))
    num_parts = max(1, len(meta.partition_index))
    num_brokers = max(1, len(meta.broker_ids))
    return dataclasses.replace(
        BASE_SPEC,
        num_brokers=num_brokers,
        num_topics=num_topics,
        partitions_per_topic=max(1, _math.ceil(num_parts / num_topics)),
        rf=max(1, min(int(state.max_replication_factor), num_brokers)),
        num_racks=max(1, len(meta.rack_names)))


def live_seed_from(cc) -> "LiveSeed | None":
    """Build the live seam from a serving facade, or None when live
    seeding is disabled or the model is not ready (callers fall back to
    the synthetic BASE_SPEC behavior)."""
    if not cc.config.get_boolean("futures.live.seed.enabled"):
        return None
    try:
        state, meta = cc.load_monitor.cluster_model()
    except Exception:  # noqa: BLE001 — monitor warming up: synthetic path
        return None
    return LiveSeed(state=state, meta=meta, config=cc.config,
                    engine=getattr(cc, "forecast_engine", None),
                    base=live_base_spec(state, meta))


@dataclasses.dataclass
class PreparedFuture:
    """A future advanced to its decision point: the model to solve, the
    per-future options, and the advance-phase bookkeeping that goes into
    its score."""

    spec: FutureSpec
    config: Any                       # the twin's CruiseControlConfig
    chain: tuple                      # goal chain (unresolved)
    state: Any                        # ClusterTensors at the decision point
    meta: Any                         # ClusterMeta
    options: Any                      # OptimizationOptions (per-future)
    events: list[dict]                # advance events actually applied
    decision: dict                    # {"removeBrokers": [...], ...}
    disk_mb: np.ndarray               # [P] per-partition disk footprint

    @property
    def future_id(self) -> str:
        return self.spec.future_id


def _prepare_live_forecast(fspec: FutureSpec, live: LiveSeed,
                           ) -> PreparedFuture:
    """The forecast_horizon future: the LIVE cluster's model with its
    load planes replaced by the forecaster's projection at the sampled
    confidence-band position — no twin, no advance; the decision solve
    runs this cluster's OWN goal chain against the loads its own
    forecaster says are coming. Falls back to the current loads (noted
    in ``decision.forecastReady``) when the engine is off or not ready,
    so the future still ranks instead of crashing the request."""
    import jax.numpy as jnp

    from ..analyzer.constraint import OptimizationOptions
    from ..analyzer.optimizer import goals_by_priority
    from ..common.resources import Resource
    from .generator import band_position
    pos = band_position(fspec.seed)
    state, meta = live.state, live.meta
    fc = None
    if live.engine is not None and live.engine.enabled:
        fc = live.engine.forecast()
    if fc is not None:
        shifted = np.maximum(
            np.asarray(fc.projected_state.leader_load) + pos * fc.band,
            0.0).astype(np.float32)
        state = dataclasses.replace(
            fc.projected_state, leader_load=jnp.asarray(shifted))
        meta = fc.meta
    disk_mb = np.asarray(state.leader_load[:, int(Resource.DISK)])
    return PreparedFuture(
        spec=fspec, config=live.config,
        chain=tuple(goals_by_priority(live.config)),
        state=state, meta=meta, options=OptimizationOptions(),
        events=[], decision={"forecastReady": fc is not None,
                             "bandPosition": pos},
        disk_mb=disk_mb)


#: Live preparers for ``requires_live`` templates, keyed by template
#: name. ``prepare_future`` dispatches here for every requires_live
#: template — a new live-only template registers its preparer alongside
#: its ``FutureTemplate`` entry or its futures raise loudly.
_LIVE_PREPARERS: dict = {"forecast_horizon": _prepare_live_forecast}


def prepare_future(fspec: FutureSpec, optimizer=None,
                   config_overrides: Mapping | None = None,
                   live: "LiveSeed | None" = None,
                   ) -> PreparedFuture:
    """Advance one future's twin to its decision point and build the
    model + options its batched solve slot needs. Host-side work only —
    no device program runs here, with ONE documented exception: the
    ``forecast_horizon`` template reads the live engine's
    GENERATION-CACHED forecast, which re-runs the one batched fit
    program (a first-shape call also compiles it) only when no fit for
    the current model generation exists — on a serving facade the
    predictive detector keeps that cache warm every interval. With
    ``live`` (the ROADMAP 5b seam) the twins take the LIVE cluster's
    geometry and the ``forecast_horizon`` template solves the live
    model under its own projected loads."""
    from .generator import FUTURE_TEMPLATES, present_future, sample_future

    tmpl = FUTURE_TEMPLATES.get(fspec.template)
    if tmpl is not None and tmpl.requires_live:
        # Generic requires_live dispatch: every live-only template MUST
        # have a registered live preparer — falling through to
        # t.sample() would silently replay a bare renamed base spec
        # under the template's name (the exact failure the what_if 400
        # guards against).
        if live is None:
            raise ValueError(
                f"template {fspec.template!r} requires the live-cluster "
                "seam — futures.live.seed.enabled on a serving facade "
                "whose model is ready (live_seed_from returns None while "
                "the monitor is still warming)")
        preparer = _LIVE_PREPARERS.get(fspec.template)
        if preparer is None:
            raise ValueError(
                f"requires_live template {fspec.template!r} has no live "
                "preparer registered in futures.evaluator._LIVE_PREPARERS")
        return preparer(fspec, live)
    base = live.base if live is not None and live.base is not None else None
    sampled = present_future() if fspec.template == PRESENT \
        else sample_future(fspec.template, fspec.seed, base=base)
    if fspec.template == PRESENT and base is not None:
        sampled = dataclasses.replace(sampled, spec=dataclasses.replace(
            base, name=PRESENT,
            description="The cluster as it is (live geometry)."))
    return prepare_sampled(sampled, fspec.ticks, optimizer=optimizer,
                           config_overrides=config_overrides, fspec=fspec)


def prepare_sampled(sampled, ticks: int, *, optimizer=None,
                    config_overrides: Mapping | None = None,
                    fspec: "FutureSpec | None" = None) -> PreparedFuture:
    """The decision-point seam under ``prepare_future``, taking an
    EXPLICIT ``SampledFuture`` instead of a (template, seed) lookup —
    the round-22 red-team miner prepares PERTURBED candidates
    (``generator.perturbed_future``) through the exact same advance +
    mark-dead + exclusion path the template futures take, so mined and
    template candidates stack into one megabatch."""
    from ..analyzer.constraint import OptimizationOptions
    from ..analyzer.optimizer import goals_by_priority
    from ..common.broker_state import BrokerState
    from ..model.tensors import set_broker_state
    from ..testing.simulator import ClusterSimulator

    if fspec is None:
        fspec = FutureSpec(sampled.template, sampled.seed, int(ticks))
    ticks = max(_MIN_TICKS, int(ticks))
    adv_events = sampled.advance_events(ticks)
    spec = dataclasses.replace(sampled.spec, ticks=ticks,
                               events=adv_events, generators=())
    overrides = {**_ADVANCE_OVERRIDES, **dict(config_overrides or {})}
    sim = ClusterSimulator(spec, seed=fspec.seed,
                           config_overrides=overrides, optimizer=optimizer)
    sim.advance(ticks)
    state, meta = sim.cc.load_monitor.cluster_model()

    idx = {bid: i for i, bid in enumerate(meta.broker_ids)}
    removed = tuple(b for b in sampled.remove_brokers if b in idx)
    added = tuple(b for b in sampled.add_brokers if b in idx)
    for b in removed:
        state = set_broker_state(state, np.int32(idx[b]),
                                 int(BrokerState.DEAD))
    for b in added:
        state = set_broker_state(state, np.int32(idx[b]),
                                 int(BrokerState.NEW))
    options = OptimizationOptions(
        excluded_brokers_for_replica_move=removed,
        excluded_brokers_for_leadership=removed)

    from ..common.resources import Resource
    disk_mb = np.asarray(state.leader_load[:, int(Resource.DISK)])
    return PreparedFuture(
        spec=fspec, config=sim.config,
        chain=tuple(goals_by_priority(sim.config)),
        state=state, meta=meta, options=options,
        events=[e.as_dict() for e in sim.events],
        decision={"removeBrokers": sorted(removed),
                  "addBrokers": sorted(added)},
        disk_mb=disk_mb)


@dataclasses.dataclass
class FutureResult:
    """One future's scored decision solve (the per-future ScenarioScore
    of the COMPARE_FUTURES response). ``error`` futures rank last."""

    future_id: str
    template: str
    seed: int
    ticks: int
    events_applied: int
    decision: dict
    error: str | None = None
    balancedness_before: float | None = None
    balancedness_after: float | None = None
    violated_goals_before: list[str] = dataclasses.field(default_factory=list)
    violated_goals_after: list[str] = dataclasses.field(default_factory=list)
    num_proposals: int = 0
    replica_moves: int = 0
    leader_moves: int = 0
    bytes_to_move_mb: float = 0.0
    rank: int = 0
    delta_vs_present: dict | None = None

    def sort_key(self) -> tuple:
        # Best balancedness first; among equals, the cheaper future
        # (fewer bytes, then proposals) wins; the id breaks exact ties
        # byte-stably. Errors rank last.
        bal = -1.0 if self.error is not None else self.balancedness_after
        return (-bal, self.bytes_to_move_mb, self.num_proposals,
                self.future_id)

    def score_dict(self) -> dict:
        return {
            "balancednessBefore": self.balancedness_before,
            "balancednessAfter": self.balancedness_after,
            "violatedGoalsBefore": self.violated_goals_before,
            "violatedGoalsAfter": self.violated_goals_after,
            "numProposals": self.num_proposals,
            "replicaMoves": self.replica_moves,
            "leaderMoves": self.leader_moves,
            "bytesToMoveMb": round(self.bytes_to_move_mb, 1),
        }

    def as_dict(self) -> dict:
        out = {
            "future": self.future_id,
            "template": self.template,
            "seed": self.seed,
            "ticks": self.ticks,
            "eventsApplied": self.events_applied,
            "decision": self.decision,
            "rank": self.rank,
            "score": self.score_dict(),
        }
        if self.error is not None:
            out["error"] = self.error
        if self.delta_vs_present is not None:
            out["deltaVsPresent"] = self.delta_vs_present
        return out


def _result_from(prepared: PreparedFuture, outcome) -> FutureResult:
    base = FutureResult(
        future_id=prepared.future_id, template=prepared.spec.template,
        seed=prepared.spec.seed, ticks=prepared.spec.ticks,
        events_applied=len(prepared.events), decision=prepared.decision)
    if isinstance(outcome, Exception):
        # Type name only: serial raises and batched slot-reconstructed
        # exceptions agree on the class, which is what a ranked answer
        # needs (full messages can differ in incidental detail).
        base.error = type(outcome).__name__
        return base
    _final, res = outcome
    replica = leader = 0
    bytes_mb = 0.0
    row_of = {tp: i for i, tp in enumerate(prepared.meta.partition_index)}
    for p in res.proposals:
        if p.is_leadership_only:
            leader += 1
        else:
            replica += 1
            row = row_of.get((p.topic, p.partition))
            if row is not None:
                bytes_mb += float(prepared.disk_mb[row])
    base.balancedness_before = round(res.balancedness_before, 3)
    base.balancedness_after = round(res.balancedness_after, 3)
    base.violated_goals_before = list(res.violated_goals_before)
    base.violated_goals_after = list(res.violated_goals_after)
    base.num_proposals = len(res.proposals)
    base.replica_moves = replica
    base.leader_moves = leader
    base.bytes_to_move_mb = bytes_mb
    return base


def _compat_key(optimizer, prepared: PreparedFuture) -> tuple:
    """The megabatch grouping key: padded bucket shape + static topic
    axis + resolved goal chain (the optimizations_megabatch
    preconditions)."""
    import jax
    shapes = tuple(jax.tree_util.tree_leaves(
        jax.tree.map(lambda x: tuple(x.shape), prepared.state)))
    return (shapes, prepared.meta.num_topics,
            tuple(optimizer.megabatch_chain(prepared.meta,
                                            list(prepared.chain))))


def evaluate_prepared(prepared: Sequence[PreparedFuture], optimizer,
                      width: int = 8, batched: bool = True,
                      occupancies: list[int] | None = None,
                      ) -> list[FutureResult]:
    """Solve every prepared future's decision model and score it.

    ``batched=True`` groups same-bucket futures and solves each group
    through ``optimizations_megabatch`` in chunks of ``width`` (one
    compiled program per bucket shape serves any occupancy; per-future
    flight passes land under ``cluster=future:<id>`` in ``GET /solver``).
    ``batched=False`` is the serial reference replay — byte-identical
    results, one device program per future (the parity pin in
    tests/test_futures.py). Results align with ``prepared`` by POSITION
    (ids are labels, not keys). When ``occupancies`` is given, the chunk
    occupancies actually solved are appended to it — the response-body
    report comes from the execution itself, never a re-derivation."""
    from ..utils.sensors import SENSORS
    results: list[FutureResult | None] = [None] * len(prepared)
    if batched:
        groups: dict[tuple, list[int]] = {}
        for i, p in enumerate(prepared):
            groups.setdefault(_compat_key(optimizer, p), []).append(i)
        for members in groups.values():
            chain = list(prepared[members[0]].chain)
            for start in range(0, len(members), max(1, int(width))):
                chunk = members[start:start + max(1, int(width))]
                items = [(prepared[i].state, prepared[i].meta,
                          f"future:{prepared[i].future_id}",
                          prepared[i].options) for i in chunk]
                out = optimizer.optimizations_megabatch(
                    items, goals=chain, width=width)
                SENSORS.observe("futures_batch_occupancy",
                                float(len(chunk)),
                                buckets=(1, 2, 4, 8, 16, 32, 64))
                if occupancies is not None:
                    occupancies.append(len(chunk))
                for i, outcome in zip(chunk, out):
                    results[i] = _result_from(prepared[i], outcome)
    else:
        for i, p in enumerate(prepared):
            try:
                outcome = optimizer.optimizations(
                    p.state, p.meta, list(p.chain), p.options)
            except Exception as e:  # noqa: BLE001 — scored, ranked last
                outcome = e
            results[i] = _result_from(p, outcome)
            if occupancies is not None:
                occupancies.append(1)
    return results


def rank_results(results: Sequence[FutureResult]) -> list[FutureResult]:
    """Rank candidate futures (present excluded from the ranking — it is
    the baseline) and attach score deltas vs the present solve."""
    present = next((r for r in results if r.future_id == PRESENT), None)
    ranked = sorted((r for r in results if r.future_id != PRESENT),
                    key=FutureResult.sort_key)
    for i, r in enumerate(ranked):
        r.rank = i + 1
        if present is not None and r.error is None \
                and present.error is None:
            r.delta_vs_present = {
                "balancednessAfter": round(
                    r.balancedness_after - present.balancedness_after, 3),
                "numProposals": r.num_proposals - present.num_proposals,
                "bytesToMoveMb": round(
                    r.bytes_to_move_mb - present.bytes_to_move_mb, 1),
            }
    return ranked


def _response_body(plan: list[FutureSpec], ranked: list[FutureResult],
                   present: FutureResult | None, batched: bool,
                   width: int, occupancies: list[int],
                   live_seeded: bool = False) -> dict:
    return {
        "operation": "compare_futures", "dryrun": True, "executed": False,
        "numFutures": len(plan),
        "ticks": plan[0].ticks if plan else 0,
        "batched": batched,
        "batchWidth": width,
        "occupancies": occupancies,
        "liveSeeded": live_seeded,
        "present": present.as_dict() if present is not None else None,
        "futures": [r.as_dict() for r in ranked],
    }


def compare_futures(templates: Sequence[str] | None = None,
                    num_futures: int = 8, seed: int = 0, ticks: int = 12,
                    optimizer=None, width: int = 8, batched: bool = True,
                    include_present: bool = True,
                    config_overrides: Mapping | None = None,
                    live: "LiveSeed | None" = None) -> dict:
    """Evaluate a batch of candidate futures end to end and return the
    ranked comparison body (the COMPARE_FUTURES response). Never touches
    the serving cluster: every future runs on its own twin, and the only
    device work is the (batched) decision solve."""
    from ..analyzer.optimizer import GoalOptimizer
    from ..utils.sensors import SENSORS
    from ..utils.tracing import TRACER
    plan = plan_futures(templates or (), num_futures, seed, ticks)
    specs = list(plan)
    if include_present:
        specs = specs + [FutureSpec(PRESENT, 0, plan[0].ticks)]
    # ccsa: ok[CCSA004] observability-only timers (sensor/span); nothing
    # wall-clock-derived enters the response body, so byte-identical
    # ranked JSON holds at one (templates, seed, ticks) request
    t0 = time.perf_counter()
    with TRACER.span("futures.evaluate", operation="futures",
                     num_futures=len(plan), ticks=plan[0].ticks,
                     batched=batched) as sp:
        prepared = []
        for fs in specs:
            prepared.append(prepare_future(
                fs, optimizer=optimizer, config_overrides=config_overrides,
                live=live))
        if optimizer is None:
            optimizer = GoalOptimizer(prepared[0].config)
        # ccsa: ok[CCSA004] observability-only timer (see t0)
        prep_s = time.perf_counter() - t0
        SENSORS.record_timer("futures_prepare", prep_s)
        occupancies: list[int] = []
        results = evaluate_prepared(prepared, optimizer, width=width,
                                    batched=batched,
                                    occupancies=occupancies)
        ranked = rank_results(results)
        present = next((r for r in results if r.future_id == PRESENT),
                       None)
        sp.set(occupancies=",".join(str(o) for o in occupancies),
               errors=sum(1 for r in results if r.error))
    SENSORS.count("futures_requests")
    SENSORS.count("futures_evaluated", len(plan))
    # ccsa: ok[CCSA004] observability-only timer (see t0)
    SENSORS.record_timer("futures_evaluate", time.perf_counter() - t0)
    return _response_body(plan, ranked, present, batched, width,
                          occupancies, live_seeded=live is not None)


class FuturesPayload:
    """MegabatchRunner payload for a fleet-scheduled COMPARE_FUTURES job:
    the request's futures prepare on the worker thread and their decision
    solves coalesce with whatever same-bucket work (paced precomputes,
    other futures requests) shares the scheduler turn — batch occupancy
    driven by user traffic, not fleet size."""

    def __init__(self, cluster_id: str,
                 templates: Sequence[str] | None, num_futures: int,
                 seed: int, ticks: int, include_present: bool = True,
                 wrap: Callable[[dict], Any] | None = None,
                 live_supplier: Callable[[], "LiveSeed | None"] | None = None):
        self.cluster_id = cluster_id
        self._plan = plan_futures(templates or (), num_futures, seed, ticks)
        self._include_present = include_present
        self._wrap = wrap
        # Live seam resolved LAZILY on the worker thread (the model
        # build belongs in the scheduler turn, not the request thread).
        self._live_supplier = live_supplier
        self._live: LiveSeed | None = None
        self._prepared: list[PreparedFuture] = []

    def prepare(self, optimizer) -> list:
        from ..fleet.megabatch import SolveItem
        specs = list(self._plan)
        if self._include_present:
            specs = specs + [FutureSpec(PRESENT, 0, self._plan[0].ticks)]
        self._live = self._live_supplier() \
            if self._live_supplier is not None else None
        self._prepared = [prepare_future(fs, optimizer=optimizer,
                                         live=self._live)
                          for fs in specs]
        return [SolveItem(item_id=f"future:{p.future_id}",
                          chain=tuple(optimizer.megabatch_chain(
                              p.meta, list(p.chain))),
                          state=p.state, meta=p.meta, options=p.options)
                for p in self._prepared]

    def complete(self, outcomes: list, stats: list) -> Any:
        from ..utils.sensors import SENSORS
        results = [_result_from(p, o)
                   for p, o in zip(self._prepared, outcomes)]
        ranked = rank_results(results)
        present = next((r for r in results if r.future_id == PRESENT),
                       None)
        # Chunk occupancies reconstructed from the runner's per-item
        # execution stats (batch_occupancy k appears once per k items of
        # that chunk; a residue means a chunk SHARED with coalesced
        # batchmates — e.g. precomputes — and still counts once). The
        # report reflects what ran, whichever scheduling path ran it.
        occs: list[int] = []
        counts: dict[int, int] = {}
        width = None
        for ds in stats:
            ds = ds or {}
            width = ds.get("batch_width", width)
            k = ds.get("batch_occupancy")
            if k:
                counts[k] = counts.get(k, 0) + 1
                if counts[k] == k:
                    occs.append(k)
                    counts[k] = 0
        occs.extend(k for k, c in counts.items() if c)
        SENSORS.count("futures_requests")
        SENSORS.count("futures_evaluated", len(self._plan))
        for k in occs:
            SENSORS.observe("futures_batch_occupancy", float(k),
                            buckets=(1, 2, 4, 8, 16, 32, 64))
        body = _response_body(self._plan, ranked, present, True,
                              width or len(self._prepared), occs,
                              live_seeded=self._live is not None)
        return self._wrap(body) if self._wrap is not None else body
