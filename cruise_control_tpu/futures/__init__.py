"""Futures engine (round 15): batched what-if scenario evaluation as a
serving workload.

- ``generator``: seeded randomized scenario templates over the digital
  twin's ``DriftSpec``/event machinery — every sampled future is a pure
  function of ``(template, seed)``.
- ``evaluator``: advances each candidate future's twin to its decision
  point, then solves ALL same-bucket futures in one megabatch-style
  device program and serves ranked ``ScenarioScore``-style comparisons.
"""

from .generator import (  # noqa: F401
    DEFAULT_TEMPLATES, FUTURE_TEMPLATES, SampledFuture, present_future,
    sample_future, sample_scenario,
)
from .evaluator import (  # noqa: F401
    PRESENT, FutureSpec, FuturesPayload, LiveSeed, compare_futures,
    evaluate_prepared, live_seed_from, plan_futures, prepare_future,
    rank_results,
)
