"""Device-mesh helpers for the sharded solver.

The solver's scale axis is the partition dimension of the cluster load
tensors (SURVEY.md §5 "long-context" mapping: N windows × M partitions,
O(brokers × replicas) search). Multi-chip runs shard that axis over a 1-D
``jax.sharding.Mesh`` named ``"p"``; broker-indexed aggregates stay
replicated and travel through ``psum`` collectives over ICI/DCN — the
TPU-native replacement for the reference's in-JVM shared-memory threading
(GoalOptimizer.java:112-119 precompute pool; SURVEY.md §2.11).
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # pre-stabilization jax: experimental home + old kwarg
    from functools import wraps

    from jax.experimental.shard_map import shard_map as _exp_shard_map

    @wraps(_exp_shard_map)
    def shard_map(*args, **kwargs):
        # The stabilized API renamed check_rep -> check_vma; translate so
        # call sites can use the current spelling everywhere.
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _exp_shard_map(*args, **kwargs)

PARTITION_AXIS = "p"


def make_mesh(n_devices: int | None = None) -> Mesh:
    """1-D mesh over the first ``n_devices`` local devices (all by default)."""
    devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices but only {len(devices)} present")
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (PARTITION_AXIS,))


def partition_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for arrays whose leading axis is the partition axis."""
    return NamedSharding(mesh, P(PARTITION_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
