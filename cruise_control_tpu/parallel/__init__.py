"""Multi-chip SPMD solver: device mesh + partition-axis-sharded search.

TPU-native replacement for the reference in-JVM concurrency (precompute
thread pool, shared mutable ClusterModel -- SURVEY.md §2.11): collectives
over ICI/DCN instead of locks.
"""

from .chain_sharded import optimize_chain_sharded
from .mesh import PARTITION_AXIS, make_mesh, partition_sharding, replicated_sharding
from .sharded import (
    optimize_goal_sharded, shard_cluster, sharded_optimize_round,
    sharded_swap_round,
)

__all__ = [
    "PARTITION_AXIS", "make_mesh", "partition_sharding", "replicated_sharding",
    "optimize_chain_sharded", "optimize_goal_sharded", "shard_cluster",
    "sharded_optimize_round", "sharded_swap_round",
]
