"""Sharded chain kernels: the WHOLE goal chain, fused, under a device mesh.

Production multi-chip solver path. One ``shard_map``-wrapped, jitted kernel
runs the entire goal chain (``lax.scan`` over the goal index; the same
structure as ``analyzer.chain.chain_optimize_full``) with:

- partition-indexed tensors sharded along the mesh axis ``"p"``, broker
  aggregates psum'd (ICI collectives) — the sharding model of
  ``parallel.sharded``;
- the active goal as a TRACED index (``lax.switch``) and prior goals as a
  traced mask — ONE compilation per (mesh, chain, search config), not the
  per-(goal, prior-chain) ``lru_cache`` blowup of the per-goal sharded
  drivers (VERDICT round 2, missing #2);
- one host dispatch and one stacked stats readback for the whole chain.

Collectives appear inside ``scan``/``while_loop``/``cond`` bodies; every
control-flow predicate is replicated (psum'd counters, the scanned goal
index), so all devices execute identical programs and the collectives
match — the same contract the fused per-goal sharded drivers rely on.

Reference parity: GoalOptimizer.java:435-524 run under SPMD instead of a
precompute thread pool (SURVEY.md §2.11 row 1).
"""

from __future__ import annotations

import dataclasses
import os
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..analyzer.candidates import (
    Candidates, CandidateDeltas, attach_cumulative, compute_deltas,
    generate_candidates, select_sources,
)
from ..analyzer.agg import (
    AggDelta, apply_deltas_to_agg, compute_agg, pot_lbi_deltas,
)
from ..analyzer.chain import (
    _chain_infos_from_stats, _gated_aux, _goal_flags, _switch_scores,
    _switch_swap_dest_score, _switch_target_dests,
    excluded_hosting_replicas,
)
from ..analyzer.constraint import BalancingConstraint
from ..analyzer.derived import compute_derived
from ..analyzer.direct import (
    _direct_rounds_driver, direct_eligible, sparse_rounding_seed,
)
from ..analyzer.fill import targets_enabled
from ..analyzer.search import (
    _OFFLINE_BONUS, _EPS_IMPROVEMENT, ExclusionMasks, SearchConfig,
    _per_broker_top_replicas, apply_selected, reduce_per_source,
    run_carry_loop,
)
from ..common.resources import Resource
from ..model.tensors import ClusterTensors, offline_replicas
from .mesh import PARTITION_AXIS, shard_map
from .sharded import _mask_specs, _psum, _state_specs, mutable_state_specs


# Per-device source-width policy for the sharded move grid. Measured on the
# 1k/100k fixture, 8 virtual devices (tools/bench_mesh.py, rounds are
# deterministic):
# - "split"  — exact num_sources//shards per device: each device surfaces
#   only its LOCAL top slice; 1,352 rounds vs 492 single-device (r4).
# - "oversample4" — 4x the split width (r4 trial): 2,513 rounds — WORSE
#   (wider per-device grids admit weaker local sources; recorded negative,
#   commit 7e538cd).
# - "full" (default) — full num_sources width per device: every device's
#   grid is a SUPERSET of the single-device grid restricted to its shard,
#   so the union covers the global top-k and the search trajectory tracks
#   the single-device one (rounds ≈ single-device). Per-device grid work
#   stays at single-device scale (redundant across devices) — on real
#   chips the non-grid phases (derived state, scores, [P]-indexed work)
#   still shard, and round-count parity is what lets 8 chips beat 1 at
#   all.
# - CC_MESH_THETA=1 additionally masks sources below the global top-k_src
#   weight threshold. Measured NEGATIVE at 1k/8dev (balancedness 86.0 →
#   83.55, extra violated goal): the mask starves the broker-diversity
#   source blocks and thins the leadership block, so it is OFF by
#   default; kept behind the env var as a measured-negative record.
_SRC_WIDTH_POLICY = os.environ.get("CC_MESH_SRC_WIDTH", "full")
_GLOBAL_THETA = os.environ.get("CC_MESH_THETA", "0") == "1"


def _per_device_source_width(num_sources: int, num_shards: int) -> int:
    if _SRC_WIDTH_POLICY == "split":
        return max(16, min(num_sources, max(1, num_sources // num_shards)))
    if _SRC_WIDTH_POLICY == "oversample4":
        return max(16, min(num_sources,
                           4 * max(1, num_sources // num_shards)))
    return num_sources  # "full"


def _global_source_threshold(weight: jax.Array, src_score: jax.Array,
                             state: ClusterTensors, k_src: int) -> jax.Array:
    """Mask ``weight`` so only the GLOBAL top-``k_src`` eligible replicas
    stay finite. Eligibility mirrors generate_candidates' on-source mask
    (replica exists, broker source-score > 0). The threshold is exact: the
    k-th largest of the union of per-device top-k covers the global top-k.
    Offline replicas carry weight 1e30, so self-healing sources always
    survive the cut."""
    from ..model.tensors import replica_exists

    b = state.num_brokers
    exists = replica_exists(state)
    seg = jnp.where(state.assignment >= 0, state.assignment, b)
    on_source = (jnp.concatenate([src_score, jnp.array([-1.0])])[seg]
                 > 0.0) & exists
    w_eff = jnp.where(on_source, weight, -jnp.inf)
    k = min(k_src, w_eff.size)
    local_top, _ = jax.lax.top_k(w_eff.reshape(-1), k)
    g_top = jax.lax.all_gather(local_top, PARTITION_AXIS).reshape(-1)
    theta = jax.lax.top_k(g_top, k)[0][-1]
    # -inf theta (fewer than k eligible replicas globally) keeps all.
    keep = w_eff >= jnp.where(jnp.isfinite(theta), theta, -jnp.inf)
    return jnp.where(keep, weight, -jnp.inf)


def _offline_per_broker(state: ClusterTensors, off: jax.Array) -> jax.Array:
    b = state.num_brokers
    seg = jnp.where(state.assignment >= 0, state.assignment, b).reshape(-1)
    local = jax.ops.segment_sum(off.astype(jnp.float32).reshape(-1), seg,
                                num_segments=b + 1)[:b]
    return _psum(local)


def _chain_scores(state, derived, active_idx, prior_mask, goals, constraint,
                  num_topics, additive_f, agg=None):
    """(aux_list, src_score, dst_score, weight) for the active goal under
    the mesh. The psum of partition-additive source scores runs
    unconditionally (collective-safety) and is selected by a traced flag."""
    is_active = jnp.arange(len(goals)) == active_idx
    aux_list = [_gated_aux(prior_mask[i] | is_active[i], g, state, derived,
                           constraint, num_topics, psum=_psum, agg=agg)
                for i, g in enumerate(goals)]
    src_score, dst_score, weight = _switch_scores(
        active_idx, goals, aux_list, state, derived, constraint)
    src_score = jnp.where(additive_f[active_idx], _psum(src_score), src_score)
    return aux_list, src_score, dst_score, weight


def _chain_round_local(state: ClusterTensors, agg, masks: ExclusionMasks,
                       active_idx: jax.Array, prior_mask: jax.Array, *,
                       goals, constraint: BalancingConstraint,
                       cfg: SearchConfig, num_topics: int, num_shards: int):
    """One chain-parameterized sharded search round (per-device body):
    the sharded analogue of ``analyzer.chain._chain_round_body``. ``agg``
    is the incrementally-maintained GLOBAL aggregate carry (replicated on
    every device; the selected batch is replicated too, so the update
    needs no further collectives). Returns (new_state, new_agg, applied)."""
    shard = jax.lax.axis_index(PARTITION_AXIS)
    p_local = state.num_partitions
    p_global = p_local * num_shards
    offset = shard * p_local
    k_src = _per_device_source_width(cfg.num_sources, num_shards)

    lead_only_f, incl_lead_f, indep_f = _goal_flags(goals)
    additive_f = jnp.asarray([g.partition_additive_scores for g in goals])
    is_lead_only = lead_only_f[active_idx]
    has_leadership = incl_lead_f[active_idx]

    derived = compute_derived(state, masks.excluded_topics,
                              masks.excluded_replica_move_brokers,
                              masks.excluded_leadership_brokers, psum=_psum,
                              agg=agg)
    is_active = jnp.arange(len(goals)) == active_idx
    aux_list, src_score, dst_score, weight = _chain_scores(
        state, derived, active_idx, prior_mask, goals, constraint,
        num_topics, additive_f, agg=agg)

    # Self-healing priority (score_round_candidates semantics).
    off = offline_replicas(state)
    offline_pb = _offline_per_broker(state, off)
    src_score = src_score + jnp.where(is_lead_only, 0.0, offline_pb)
    weight = jnp.where(off & ~is_lead_only, 1e30, weight)
    if _GLOBAL_THETA and num_shards > 1:
        weight = _global_source_threshold(weight, src_score, state, k_src)

    # Targeted-destination column (Goal.target_dests). Card fill ranks
    # are device-local against a REPLICATED deficit/headroom profile, so
    # a naive fill has every device claim the same positions — measured
    # at 1k/8dev that drops balancedness 86.0 → 74.5 with three extra
    # violated goals. The SHARD-OFFSET fill (device d's cards take
    # interleaved global positions rank·num_shards + d, CC_MESH_TARGETS=1
    # to enable) fixes the quality collapse — measured 86.0 with the
    # violated set pinned — but buys NO round reduction (672 vs 667 at
    # 1k/8dev: the mesh's round inflation lives in selection, not
    # destination starvation), so the default keeps the targeted branch
    # off the mesh and its per-round cost with it.
    # Scale gate on the GLOBAL partition count (p_local * num_shards):
    # the threshold's measured meaning is cluster scale.
    extra = None
    use_targets = targets_enabled(p_global) and (
        num_shards == 1 or os.environ.get("CC_MESH_TARGETS") == "1")
    if use_targets:
        cand_p, cand_s, src_valid = select_sources(state, src_score, weight,
                                                   k_src)
        t_dst, t_ok = _switch_target_dests(active_idx, goals, aux_list,
                                           state, derived, constraint,
                                           cand_p, cand_s, src_valid,
                                           rank_stride=num_shards,
                                           rank_offset=shard)
        # Targets pause while any offline replica exists ANYWHERE on the
        # mesh (psum'd below via offline_pb; see chain._chain_round_body).
        extra = (t_dst, t_ok & ~(_psum(off.sum()) > 0))
    cand, layout = generate_candidates(state, derived, src_score, dst_score,
                                       weight, k_src, cfg.num_dests,
                                       include_leadership=True,
                                       leadership_only=False,
                                       extra_dst=extra)
    (r0, c0), (r1, c1) = layout
    block_ok = jnp.concatenate([
        jnp.broadcast_to(~is_lead_only, (r0 * c0,)),
        jnp.broadcast_to(has_leadership, (r1 * c1,)),
    ])
    cand = dataclasses.replace(cand, valid=cand.valid & block_ok)
    deltas = compute_deltas(state, derived, cand)

    accept = deltas.valid
    for i, g in enumerate(goals):
        accept &= (~prior_mask[i]) | g.acceptance(state, derived, constraint,
                                                  aux_list[i], deltas)

    moving_offline = off[deltas.partition, deltas.src_slot] \
        & (deltas.replica_delta > 0)

    def imp_branch(i):
        g = goals[i]

        def fn(_):
            return g.improvement(state, derived, constraint, aux_list[i],
                                 deltas).astype(jnp.float32)
        return fn

    imp = jax.lax.switch(active_idx,
                         [imp_branch(i) for i in range(len(goals))], 0)
    imp = jnp.where(moving_offline & jnp.isfinite(imp) & deltas.valid,
                    jnp.maximum(imp, 0.0) + _OFFLINE_BONUS, imp)
    score = jnp.where(accept, imp, -jnp.inf)

    # Device-decorrelating rotation offset: with thin per-device slices
    # different devices should lean toward different destinations among
    # ties; with FULL-width grids each device already holds distinct
    # (local) sources. Measured at 1k/8dev: zeroing the offset
    # (CC_MESH_ROT=flat) is neutral — 649 vs 667 rounds at identical
    # quality — so the offset stays (it strictly helps thinner widths).
    rot_offset = 0 if os.environ.get("CC_MESH_ROT") == "flat" \
        else shard * k_src
    red_idx = reduce_per_source(
        score, layout, row_offset=rot_offset, extra_last_col=use_targets)
    k_local = red_idx.shape[0]

    def gather(x):
        return jax.lax.all_gather(x, PARTITION_AXIS).reshape(
            (num_shards * x.shape[0],) + x.shape[1:])

    # Per-candidate scalars that need LOCAL partition state are computed
    # pre-gather (global partition ids cannot be gathered against the local
    # shard); everything the joint-acceptance recheck needs travels with
    # the candidate card.
    local_sub = jax.tree.map(lambda a: a[red_idx], deltas)
    pot_local, lbi_local = pot_lbi_deltas(state, local_sub)

    g_sub = jax.tree.map(gather, local_sub)
    g_sub = dataclasses.replace(g_sub, partition=gather(
        local_sub.partition + offset))
    g_score = gather(score[red_idx])
    g_pot = gather(pot_local)
    g_lbi = gather(lbi_local)
    g_dslot = gather(cand.dst_slot[red_idx])
    g_kind = gather(cand.kind[red_idx])

    # Joint (cumulative) conflict selection, replicated: rank by score,
    # dedupe partitions, pairwise pre-deltas in RANK order over the
    # device-concatenated card array (search.cumulative_select semantics,
    # inlined because rank != array order here).
    k_global = num_shards * k_local
    k = min(max(cfg.moves_per_round, cfg.num_sources), k_global)
    top_score, order = jax.lax.top_k(g_score, k)
    ranked = jax.tree.map(lambda a: a[order], g_sub)
    ok = top_score > _EPS_IMPROVEMENT
    rank = jnp.arange(k, dtype=jnp.int32)
    big = jnp.int32(k + 1)
    rank_eff = jnp.where(ok, rank, big)
    first_p = jnp.full(p_global, big, jnp.int32) \
        .at[ranked.partition].min(rank_eff)
    part_ok = ok & (first_p[ranked.partition] == rank)
    ranked, has_earlier = attach_cumulative(ranked, part_ok, g_pot[order],
                                            g_lbi[order])

    # Acceptance recheck: per-BROKER state (derived, aux) is replicated, so
    # every device evaluates the full ranked batch identically — structural
    # per-partition terms were already folded into pass-1 acceptance (the
    # score), and per-partition scalars (pot/lbi) travel with the cards, so
    # goal.acceptance here must only touch broker-indexed state. All the
    # stacked goals' acceptance implementations satisfy that except the
    # structural ones, whose acceptance ignores the pre fields and repeats
    # the (partition-local) pass-1 verdict — evaluate those on the OWNING
    # device and gather. To keep one code path, the recheck gates on
    # ownership masks.
    own = (ranked.partition >= offset) & (ranked.partition < offset + p_local)
    local_rows = jnp.clip(ranked.partition - offset, 0, p_local - 1)
    local_view = dataclasses.replace(ranked, partition=local_rows)

    accept = jnp.ones(k, dtype=bool)
    for i, g in enumerate(goals):
        g_acc = g.acceptance(state, derived, constraint, aux_list[i],
                             local_view)
        # Rows this device does not own read clamped partition state —
        # meaningless; trust the owner: psum of (owner's verdict), since
        # exactly one device owns each row.
        g_acc_owned = _psum(jnp.where(own, g_acc, False).astype(jnp.int32)) > 0
        accept &= (~prior_mask[i]) | g_acc_owned
        accept &= (~is_active[i]) | (~has_earlier) | g_acc_owned

    independent = indep_f[active_idx] & ~prior_mask.any()
    sel = part_ok & accept
    within_cap = jnp.cumsum(sel.astype(jnp.int32)) <= cfg.moves_per_round
    sel &= jnp.where(independent, True, within_cap)

    # ``sel`` is computed from gathered, replicated data — identical on
    # every device, so its sum is already the global count, and the
    # aggregate-carry update below stays replicated device-for-device.
    if agg is not None:
        agg = apply_deltas_to_agg(agg, ranked, sel, g_pot[order],
                                  g_lbi[order])
    new_state = apply_selected(state, sel, ranked.partition,
                               ranked.src_slot, ranked.dst_broker,
                               g_kind[order], g_dslot[order],
                               row_offset=offset)
    return new_state, agg, sel.sum()


def _chain_swap_local(state: ClusterTensors, agg, masks: ExclusionMasks,
                      active_idx: jax.Array, prior_mask: jax.Array, *,
                      goals, constraint: BalancingConstraint, num_topics: int,
                      num_shards: int, k_brokers: int = 8,
                      j_replicas: int = 4, moves: int = 8):
    """Chain-parameterized sharded swap round — the card-gather kernel of
    ``parallel.sharded._swap_round_local`` with the active goal as a traced
    switch and prior acceptance as a traced mask. ``agg`` as in
    ``_chain_round_local``; returns (new_state, new_agg, applied)."""
    shard = jax.lax.axis_index(PARTITION_AXIS)
    p_local = state.num_partitions
    p_global = p_local * num_shards
    offset = shard * p_local
    b = state.num_brokers
    s_dim = state.max_replication_factor
    j = j_replicas

    additive_f = jnp.asarray([g.partition_additive_scores for g in goals])
    derived = compute_derived(state, masks.excluded_topics,
                              masks.excluded_replica_move_brokers,
                              masks.excluded_leadership_brokers, psum=_psum,
                              agg=agg)
    aux_list, src_score, _dst_score, weight = _chain_scores(
        state, derived, active_idx, prior_mask, goals, constraint,
        num_topics, additive_f, agg=agg)

    # Swap counterparties rank by swap_dest_score (broker-indexed, mesh-
    # safe). NOTE: swap IMPROVEMENT on the mesh stays net-transfer-based
    # (goal.improvement(net)) — leg-scored overrides (swap_improvement)
    # need the legs' partition-local state, which lives on the owning
    # device; the kafka-assigner tool mode that relies on leg scoring
    # runs single-device.
    dst_score = _switch_swap_dest_score(active_idx, goals, aux_list, state,
                                        derived, constraint)

    k = min(k_brokers, b)
    src_vals, src_brokers = jax.lax.top_k(
        jnp.where(src_score > 0, src_score, -jnp.inf), k)
    dst_vals, dst_brokers = jax.lax.top_k(dst_score, k)
    src_b_ok = jnp.isfinite(src_vals)
    dst_b_ok = jnp.isfinite(dst_vals)

    heavy_idx, heavy_ok = _per_broker_top_replicas(
        state, weight, src_brokers, j, largest=True)
    light_idx, light_ok = _per_broker_top_replicas(
        state, weight, dst_brokers, j, largest=False)

    p1, s1 = heavy_idx // s_dim, heavy_idx % s_dim
    p2, s2 = light_idx // s_dim, light_idx % s_dim

    def leg_masks(pp, ss, ok, counterparties):
        n = k * j * k
        cand = Candidates(
            kind=jnp.zeros(n, dtype=jnp.int8),
            partition=jnp.broadcast_to(pp[:, :, None], (k, j, k)).reshape(-1),
            src_slot=jnp.broadcast_to(ss[:, :, None], (k, j, k)).reshape(-1),
            dst_broker=jnp.broadcast_to(counterparties[None, None, :],
                                        (k, j, k)).reshape(-1),
            dst_slot=jnp.zeros(n, dtype=jnp.int32),
            valid=jnp.broadcast_to(ok[:, :, None], (k, j, k)).reshape(-1))
        d = compute_deltas(state, derived, cand)
        acc = d.valid
        for i, g in enumerate(goals):
            acc &= (~prior_mask[i]) | g.swap_leg_acceptance(
                state, derived, constraint, aux_list[i], d)
        return acc.reshape(k, j, k)

    leg_f = leg_masks(p1, s1, heavy_ok, dst_brokers)
    leg_r = leg_masks(p2, s2, light_ok, src_brokers)

    w_a = jnp.where(heavy_ok, weight[p1, s1], -jnp.inf)
    w_b = jnp.where(light_ok, weight[p2, s2], jnp.inf)
    lead1 = state.leader_slot[p1] == s1
    lead2 = state.leader_slot[p2] == s2
    load_a = jnp.where(lead1[..., None], state.leader_load[p1],
                       state.follower_load[p1])
    load_b = jnp.where(lead2[..., None], state.leader_load[p2],
                       state.follower_load[p2])
    gp1, gp2 = p1 + offset, p2 + offset
    top1 = state.topic[p1]
    top2 = state.topic[p2]
    nwout1 = state.leader_load[p1, int(Resource.NW_OUT)]
    nwout2 = state.leader_load[p2, int(Resource.NW_OUT)]
    nwin1 = state.leader_load[p1, int(Resource.NW_IN)]
    nwin2 = state.leader_load[p2, int(Resource.NW_IN)]

    def gather_cards(x):
        y = jax.lax.all_gather(x, PARTITION_AXIS)
        y = jnp.moveaxis(y, 0, 1)
        return y.reshape((k, num_shards * j) + y.shape[3:])

    g_wa = gather_cards(w_a)
    g_wb = gather_cards(w_b)
    hv, hsel = jax.lax.top_k(g_wa, j)
    lv, lsel = jax.lax.top_k(-g_wb, j)
    heavy_ok_g = jnp.isfinite(hv)
    light_ok_g = jnp.isfinite(lv)

    def pick(gathered, sel):
        extra = gathered.ndim - 2
        return jnp.take_along_axis(
            gathered, sel.reshape(sel.shape + (1,) * extra), axis=1)

    h_load = pick(gather_cards(load_a), hsel)
    l_load = pick(gather_cards(load_b), lsel)
    h_lead = pick(gather_cards(lead1), hsel)
    l_lead = pick(gather_cards(lead2), lsel)
    h_gp = pick(gather_cards(gp1), hsel)
    l_gp = pick(gather_cards(gp2), lsel)
    h_s = pick(gather_cards(s1), hsel)
    l_s = pick(gather_cards(s2), lsel)
    h_topic = pick(gather_cards(top1), hsel)
    l_topic = pick(gather_cards(top2), lsel)
    h_nwout = pick(gather_cards(nwout1), hsel)
    l_nwout = pick(gather_cards(nwout2), lsel)
    h_nwin = pick(gather_cards(nwin1), hsel)
    l_nwin = pick(gather_cards(nwin2), lsel)
    h_legs = pick(gather_cards(leg_f), hsel)
    l_legs = pick(gather_cards(leg_r), lsel)
    h_w = hv
    l_w = -lv

    n = k * k * j * j
    si, di, ai, bi = jnp.meshgrid(jnp.arange(k), jnp.arange(k),
                                  jnp.arange(j), jnp.arange(j), indexing="ij")
    si, di, ai, bi = (x.reshape(-1) for x in (si, di, ai, bi))
    src_b = src_brokers[si]
    dst_b = dst_brokers[di]
    wa = h_w[si, ai]
    wb = l_w[di, bi]
    sel_gp1 = h_gp[si, ai]
    sel_gp2 = l_gp[di, bi]

    base_valid = src_b_ok[si] & dst_b_ok[di] & heavy_ok_g[si, ai] \
        & light_ok_g[di, bi] & (src_b != dst_b) & (sel_gp1 != sel_gp2) \
        & (wa > wb) & h_legs[si, ai, di] & l_legs[di, bi, si]

    lead_d = h_lead[si, ai].astype(jnp.int32) - l_lead[di, bi].astype(jnp.int32)
    net_load = h_load[si, ai] - l_load[di, bi]
    net = CandidateDeltas(
        src_broker=jnp.where(base_valid, src_b, 0),
        dst_broker=jnp.where(base_valid, dst_b, 0),
        load_delta=jnp.where(base_valid[:, None], net_load, 0.0),
        replica_delta=jnp.zeros(n, dtype=jnp.int32),
        leader_delta=jnp.where(base_valid, lead_d, 0),
        partition=sel_gp1, topic=h_topic[si, ai],
        src_slot=h_s[si, ai], dst_slot=jnp.zeros(n, dtype=jnp.int32),
        valid=base_valid)

    accept = base_valid
    for i, g in enumerate(goals):
        accept &= (~prior_mask[i]) | g.swap_net_acceptance(
            state, derived, constraint, aux_list[i], net)

    def imp_branch(i):
        g = goals[i]

        def fn(_):
            return g.improvement(state, derived, constraint, aux_list[i],
                                 net).astype(jnp.float32)
        return fn

    imp = jax.lax.switch(active_idx,
                         [imp_branch(i) for i in range(len(goals))], 0)
    score = jnp.where(accept, imp, -jnp.inf)

    k_m = min(moves, n)
    top_score, top_idx = jax.lax.top_k(score, k_m)
    ok = top_score > _EPS_IMPROVEMENT
    rank = jnp.arange(k_m, dtype=jnp.int32)
    big = jnp.int32(k_m + 1)
    rank_eff = jnp.where(ok, rank, big)
    t_gp1, t_gp2 = sel_gp1[top_idx], sel_gp2[top_idx]
    t_src, t_dst = src_b[top_idx], dst_b[top_idx]
    first_part = jnp.full(p_global, big, jnp.int32) \
        .at[t_gp1].min(rank_eff).at[t_gp2].min(rank_eff)
    first_broker = jnp.full(b, big, jnp.int32) \
        .at[t_src].min(rank_eff).at[t_dst].min(rank_eff)
    sel = ok & (first_part[t_gp1] == rank) & (first_part[t_gp2] == rank) \
        & (first_broker[t_src] == rank) & (first_broker[t_dst] == rank)

    if agg is not None:
        # Replicated leg updates (see _chain_round_local): both directional
        # legs of each accepted swap scatter their exact effect.
        ones = jnp.ones(k_m, dtype=jnp.int32)
        h_lead_t = h_lead[si, ai][top_idx].astype(jnp.int32)
        l_lead_t = l_lead[di, bi][top_idx].astype(jnp.int32)
        fwd_leg = AggDelta(
            src_broker=t_src, dst_broker=t_dst,
            load_delta=h_load[si, ai][top_idx], replica_delta=ones,
            leader_delta=h_lead_t, topic=h_topic[si, ai][top_idx])
        rev_leg = AggDelta(
            src_broker=t_dst, dst_broker=t_src,
            load_delta=l_load[di, bi][top_idx], replica_delta=ones,
            leader_delta=l_lead_t, topic=l_topic[di, bi][top_idx])
        agg = apply_deltas_to_agg(
            agg, fwd_leg, sel, h_nwout[si, ai][top_idx],
            h_lead_t * h_nwin[si, ai][top_idx])
        agg = apply_deltas_to_agg(
            agg, rev_leg, sel, l_nwout[di, bi][top_idx],
            l_lead_t * l_nwin[di, bi][top_idx])

    p_pad = jnp.int32(p_local)
    row1 = t_gp1 - offset
    row2 = t_gp2 - offset
    rows1 = jnp.where(sel & (row1 >= 0) & (row1 < p_local), row1, p_pad)
    rows2 = jnp.where(sel & (row2 >= 0) & (row2 < p_local), row2, p_pad)
    new_assignment = state.assignment \
        .at[rows1, h_s[si, ai][top_idx]].set(
            t_dst.astype(state.assignment.dtype), mode="drop") \
        .at[rows2, l_s[di, bi][top_idx]].set(
            t_src.astype(state.assignment.dtype), mode="drop")
    return dataclasses.replace(state, assignment=new_assignment), agg, sel.sum()


def _chain_stats_local(state: ClusterTensors, masks: ExclusionMasks,
                       active_idx: jax.Array, *, goals,
                       constraint: BalancingConstraint, num_topics: int):
    """(viol, obj, offline) of the active goal under the mesh. Dispatches
    through ``Goal.objective`` like the single-device stats body; a goal
    with ``partition_additive_scores`` must keep any objective override
    partition-additive too (it is psum'd here)."""
    additive_f = jnp.asarray([g.partition_additive_scores for g in goals])
    derived = compute_derived(state, masks.excluded_topics,
                              masks.excluded_replica_move_brokers,
                              masks.excluded_leadership_brokers, psum=_psum)
    is_active = jnp.arange(len(goals)) == active_idx
    aux_list = [_gated_aux(is_active[i], g, state, derived, constraint,
                           num_topics, psum=_psum)
                for i, g in enumerate(goals)]

    def branch(i):
        g = goals[i]

        def fn(_):
            viol = g.broker_violations(state, derived, constraint,
                                       aux_list[i]).sum().astype(jnp.float32)
            obj = g.objective(state, derived, constraint,
                              aux_list[i]).astype(jnp.float32)
            return viol, obj
        return fn

    viol, obj = jax.lax.switch(active_idx,
                               [branch(i) for i in range(len(goals))], 0)
    viol = jnp.where(additive_f[active_idx], _psum(viol), viol)
    obj = jnp.where(additive_f[active_idx], _psum(obj), obj)
    offline = _psum(offline_replicas(state).sum())
    return viol, obj, offline


def _chain_full_local(state: ClusterTensors, masks: ExclusionMasks, *,
                      goals, constraint: BalancingConstraint,
                      cfg: SearchConfig, num_topics: int, num_shards: int,
                      swap_moves: int, swap_max_rounds: int):
    """Per-device body of the whole-chain kernel (the sharded analogue of
    ``analyzer.chain.chain_optimize_full``'s traced body)."""
    g_count = len(goals)
    supports_swap = jnp.asarray([g.supports_swap for g in goals])

    def drain_pending(s: ClusterTensors) -> jax.Array:
        if masks.excluded_replica_move_brokers is None:
            return jnp.bool_(False)
        on_excl = excluded_hosting_replicas(
            s, masks.excluded_replica_move_brokers)
        return _psum(on_excl.sum()) > 0  # replicated predicate on the mesh

    def per_goal(carry_state, g):
        prior = jnp.arange(g_count) < g
        viol0, obj0, offline0 = _chain_stats_local(
            carry_state, masks, g, goals=goals, constraint=constraint,
            num_topics=num_topics)

        def run(s):
            # Aggregate carry: psum'd -> global, replicated, threaded
            # through both phases. A cond-GATED in-loop refresh would be
            # collective-unsafe, but while_loop bodies execute collectives
            # unconditionally on every device, so an ungated recompute at
            # the top of each outer iteration is safe — it bounds f32
            # drift to one move+swap cycle instead of a full
            # cfg.max_rounds pass (ADVICE r4; counts stay exact always).
            def outer_cond(c):
                _s, _a, _m, _sw, rounds, last_swapped, first = c
                return (first | (last_swapped > 0)) & (rounds < cfg.max_rounds)

            def outer_body(c):
                s, _a, m_tot, sw_tot, rounds, _ls, _first = c
                a = compute_agg(s, num_topics, psum=_psum)

                def move_body(carry, _r):
                    st, ag = carry
                    ns, nag, applied = _chain_round_local(
                        st, ag, masks, g, prior, goals=goals,
                        constraint=constraint, cfg=cfg,
                        num_topics=num_topics, num_shards=num_shards)
                    return (ns, nag), applied

                (s, a), m, r = run_carry_loop(move_body, (s, a),
                                              cfg.max_rounds)

                def do_swap(st_ag):
                    def swap_body(carry, _r):
                        st, ag = carry
                        ns, nag, applied = _chain_swap_local(
                            st, ag, masks, g, prior, goals=goals,
                            constraint=constraint, num_topics=num_topics,
                            num_shards=num_shards, moves=swap_moves)
                        return (ns, nag), applied

                    (st, ag), sw, sr = run_carry_loop(swap_body, st_ag,
                                                      swap_max_rounds)
                    return st, ag, sw, sr

                def no_swap(st_ag):
                    st, ag = st_ag
                    return st, ag, jnp.int32(0), jnp.int32(0)

                s, a, sw, sr = jax.lax.cond(supports_swap[g], do_swap,
                                            no_swap, (s, a))
                return (s, a, m_tot + m, sw_tot + sw, rounds + r + sr, sw,
                        jnp.bool_(False))

            s, a, m, sw, rounds, _, _ = jax.lax.while_loop(
                outer_cond, outer_body,
                (s, compute_agg(s, num_topics, psum=_psum), jnp.int32(0),
                 jnp.int32(0), jnp.int32(0), jnp.int32(0), jnp.bool_(True)))
            return s, m, sw, rounds

        def skip(s):
            return s, jnp.int32(0), jnp.int32(0), jnp.int32(0)

        new_state, moves, swaps, rounds = jax.lax.cond(
            (viol0 > 0) | (offline0 > 0) | drain_pending(carry_state),
            run, skip, carry_state)
        viol1, obj1, offline1 = _chain_stats_local(
            new_state, masks, g, goals=goals, constraint=constraint,
            num_topics=num_topics)
        ys = {"viol_before": viol0, "obj_before": obj0,
              "offline_before": offline0, "viol_after": viol1,
              "obj_after": obj1, "offline_after": offline1,
              "moves": moves, "swaps": swaps, "rounds": rounds}
        return new_state, ys

    return jax.lax.scan(per_goal, state, jnp.arange(g_count, dtype=jnp.int32))


@lru_cache(maxsize=64)
def _make_chain_full(mesh: Mesh, goals, constraint, cfg: SearchConfig,
                     num_topics: int, mask_presence: tuple[bool, bool, bool],
                     swap_moves: int, swap_max_rounds: int):
    """ONE compile per (mesh, chain, search config) — the whole chain."""
    body = partial(_chain_full_local, goals=goals, constraint=constraint,
                   cfg=cfg, num_topics=num_topics,
                   num_shards=mesh.devices.size, swap_moves=swap_moves,
                   swap_max_rounds=swap_max_rounds)
    stats_specs = {k: P() for k in
                   ("viol_before", "obj_before", "offline_before",
                    "viol_after", "obj_after", "offline_after",
                    "moves", "swaps", "rounds")}
    mapped = shard_map(body, mesh=mesh,
                       in_specs=(_state_specs(), _mask_specs(mask_presence)),
                       out_specs=(_state_specs(), stats_specs),
                       check_vma=False)
    return jax.jit(mapped)


def optimize_chain_sharded(state: ClusterTensors, chain,
                           constraint: BalancingConstraint, cfg: SearchConfig,
                           num_topics: int, mesh: Mesh,
                           masks: ExclusionMasks | None = None,
                           swap_moves: int = 8, swap_max_rounds: int = 64,
                           dispatch_rounds: int = 0,
                           dispatch_target_s: float = 0.0,
                           dispatch=None, dispatch_wide=None,
                           megastep=None, stats=None,
                           donate_input: bool = False,
                           flight=None,
                           ) -> tuple[ClusterTensors, list[dict]]:
    """Sharded analogue of ``analyzer.chain.optimize_chain``: the whole
    chain in one dispatch over the mesh, same info-dict contract and error
    behavior (hard-goal failure / stats-regression raised per goal in chain
    order from the stacked stats).

    ``dispatch_rounds`` > 0 selects the bounded per-goal driver instead —
    same kernels and trajectory, ≤ that many search rounds per device
    dispatch (the large-cluster watchdog mitigation of
    ``analyzer.chain.optimize_goal_in_chain``, under the mesh), driven as
    donated megastep dispatches with asynchronous stats readback when
    ``megastep`` (chain.MegastepConfig) asks for them. ``dispatch`` /
    ``dispatch_wide`` pass the optimizer's persistent per-shape
    controllers: deficit-sized count goals run wide-cost-class rounds
    and are billed to ``dispatch_wide`` so they cannot overshoot (then
    depress) the base-width budget. ``donate_input`` declares the
    caller relinquishes ``state`` (e.g. a fresh shard_cluster
    placement) so even the first dispatch may donate. ``flight`` (a
    utils.flight_recorder pass handle) records per-goal entry/exit
    violations, sizing decisions, and per-dispatch telemetry on the
    bounded path — at DISPATCH granularity: the per-round stats ring is
    single-device machinery (its reductions would need extra collectives
    under the mesh)."""
    masks = masks or ExclusionMasks()
    goals = tuple(chain)
    if not goals:
        return state, []
    presence = (masks.excluded_topics is not None,
                masks.excluded_replica_move_brokers is not None,
                masks.excluded_leadership_brokers is not None)
    if dispatch_rounds > 0:
        return _optimize_chain_sharded_bounded(
            state, goals, constraint, cfg, num_topics, mesh, masks, presence,
            swap_moves, swap_max_rounds, dispatch_rounds, dispatch_target_s,
            dispatch=dispatch, dispatch_wide=dispatch_wide,
            megastep=megastep, stats=stats, donate_input=donate_input,
            flight=flight)
    fn = _make_chain_full(mesh, goals, constraint, cfg, num_topics, presence,
                          swap_moves, swap_max_rounds)
    state, stats_dev = fn(state, masks)
    stats_dev = {k: jax.device_get(v) for k, v in stats_dev.items()}
    return state, _chain_infos_from_stats(goals, stats_dev)


@lru_cache(maxsize=64)
def _make_chain_phase_kernels(mesh: Mesh, goals, constraint,
                              cfg: SearchConfig, num_topics: int,
                              mask_presence: tuple[bool, bool, bool],
                              swap_moves: int, swap_max_rounds: int):
    """Per-goal sharded kernels (move pass / swap pass / stats), each ONE
    compile for the whole chain via traced (active_idx, prior_mask) — the
    bounded-dispatch counterparts of ``_make_chain_full``."""
    shards = mesh.devices.size
    rep = P()  # replicated scalars

    def move_body(state, masks, active_idx, prior_mask, budget):
        def body(carry, _r):
            st, ag = carry
            ns, nag, applied = _chain_round_local(
                st, ag, masks, active_idx, prior_mask, goals=goals,
                constraint=constraint, cfg=cfg, num_topics=num_topics,
                num_shards=shards)
            return (ns, nag), applied

        (st, _a), total, rounds = run_carry_loop(
            body, (state, compute_agg(state, num_topics, psum=_psum)),
            cfg.max_rounds, budget=budget)
        return st, total, rounds

    def swap_body(state, masks, active_idx, prior_mask, budget):
        def body(carry, _r):
            st, ag = carry
            ns, nag, applied = _chain_swap_local(
                st, ag, masks, active_idx, prior_mask, goals=goals,
                constraint=constraint, num_topics=num_topics,
                num_shards=shards, moves=swap_moves)
            return (ns, nag), applied

        (st, _a), total, rounds = run_carry_loop(
            body, (state, compute_agg(state, num_topics, psum=_psum)),
            swap_max_rounds, budget=budget)
        return st, total, rounds

    def stats_body(state, masks, active_idx):
        return _chain_stats_local(state, masks, active_idx, goals=goals,
                                  constraint=constraint,
                                  num_topics=num_topics)

    def move_body_donated(assignment, leader_slot, rest, masks, active_idx,
                          prior_mask, budget):
        state = dataclasses.replace(rest, assignment=assignment,
                                    leader_slot=leader_slot)
        st, total, rounds = move_body(state, masks, active_idx, prior_mask,
                                      budget)
        return st.assignment, st.leader_slot, total, rounds

    def swap_body_donated(assignment, leader_slot, rest, masks, active_idx,
                          prior_mask, budget):
        state = dataclasses.replace(rest, assignment=assignment,
                                    leader_slot=leader_slot)
        st, total, rounds = swap_body(state, masks, active_idx, prior_mask,
                                      budget)
        return st.assignment, st.leader_slot, total, rounds

    mask_specs = _mask_specs(mask_presence)
    part_a, part_l = mutable_state_specs()
    move = jax.jit(shard_map(
        move_body, mesh=mesh,
        in_specs=(_state_specs(), mask_specs, rep, rep, rep),
        out_specs=(_state_specs(), rep, rep), check_vma=False))
    swap = jax.jit(shard_map(
        swap_body, mesh=mesh,
        in_specs=(_state_specs(), mask_specs, rep, rep, rep),
        out_specs=(_state_specs(), rep, rep), check_vma=False))
    # Donated megastep variants (chain.chain_optimize_rounds_donated under
    # the mesh): the two mutable tensors ride as separate donated
    # arguments so XLA rewrites the sharded assignment in place — the
    # read-only remainder (strip_mutable) keeps the topology tensors out
    # of the donation set.
    move_d = jax.jit(shard_map(
        move_body_donated, mesh=mesh,
        in_specs=(part_a, part_l, _state_specs(), mask_specs, rep, rep, rep),
        out_specs=(part_a, part_l, rep, rep), check_vma=False),
        donate_argnums=(0, 1))
    swap_d = jax.jit(shard_map(
        swap_body_donated, mesh=mesh,
        in_specs=(part_a, part_l, _state_specs(), mask_specs, rep, rep, rep),
        out_specs=(part_a, part_l, rep, rep), check_vma=False),
        donate_argnums=(0, 1))
    stats = jax.jit(shard_map(
        stats_body, mesh=mesh,
        in_specs=(_state_specs(), mask_specs, rep),
        out_specs=(rep, rep, rep), check_vma=False))
    return move, swap, stats, move_d, swap_d


@lru_cache(maxsize=64)
def _make_direct_phase_kernels(mesh: Mesh, goals, index: int, constraint,
                               num_topics: int,
                               mask_presence: tuple[bool, bool, bool],
                               max_sweeps: int, margin_frac: float,
                               seed: int):
    """Sharded direct-transport kernel pair for ONE goal index. Unlike
    the move/swap kernels (traced ``active_idx`` + prior mask, one
    compile per chain), the direct sweep bodies are selected by
    TRACE-TIME Python dispatch on the goal index (``_sweep_fn`` /
    ``_guards_for`` build the guard closure from ``goals[:index]``), so
    the mesh kernel is built per-(mesh, index) — the lru_cache bounds
    the set to the direct-eligible count goals actually reached.

    The body is the SAME sweep driver as the single-device path, run
    per-shard under the interleaved rank layout: every device ranks only
    its local replica rows but occupies global fill positions
    ``local_rank * num_shards + device`` (``rank_stride``/``block``), so
    the union of per-device movers tiles each cell's surplus exactly —
    no device claims another's positions and the joint plan equals the
    single-device plan under a row permutation. Count/load caps budget
    each device ``1/num_shards`` of every band, and the returned scalars
    are psum'd global, so the while-loop predicate agrees across devices
    by construction."""
    shards = mesh.devices.size
    rep = P()

    def direct_body(state, masks):
        return _direct_rounds_driver(
            state, goals, index, constraint, num_topics, masks, max_sweeps,
            rank_stride=shards, block=jax.lax.axis_index(PARTITION_AXIS),
            psum=_psum, margin_frac=margin_frac, seed=seed)

    def direct_body_donated(assignment, leader_slot, rest, masks):
        st = dataclasses.replace(rest, assignment=assignment,
                                 leader_slot=leader_slot)
        final, total, sweeps, planned = direct_body(st, masks)
        return final.assignment, final.leader_slot, total, sweeps, planned

    mask_specs = _mask_specs(mask_presence)
    part_a, part_l = mutable_state_specs()
    direct_k = jax.jit(shard_map(
        direct_body, mesh=mesh,
        in_specs=(_state_specs(), mask_specs),
        out_specs=(_state_specs(), rep, rep, rep), check_vma=False))
    direct_d = jax.jit(shard_map(
        direct_body_donated, mesh=mesh,
        in_specs=(part_a, part_l, _state_specs(), mask_specs),
        out_specs=(part_a, part_l, rep, rep, rep), check_vma=False),
        donate_argnums=(0, 1))
    return direct_k, direct_d


def _optimize_chain_sharded_bounded(state, goals, constraint, cfg,
                                    num_topics, mesh, masks, presence,
                                    swap_moves, swap_max_rounds,
                                    dispatch_rounds: int,
                                    dispatch_target_s: float = 0.0,
                                    dispatch=None, dispatch_wide=None,
                                    megastep=None, stats=None,
                                    donate_input: bool = False,
                                    flight=None,
                                    ) -> tuple[ClusterTensors, list[dict]]:
    """Host-looped per-goal sharded driver: the trajectory of
    ``_chain_full_local`` with every device dispatch bounded — starting at
    ``dispatch_rounds`` search rounds and adaptively resized toward
    ``dispatch_target_s`` of wall-clock per dispatch (AdaptiveDispatch;
    ``dispatch`` passes the optimizer's persistent per-shape controller
    so mesh precomputes keep their learned budget across passes), pumped
    as donated megasteps with async stats readback per ``megastep``
    (analyzer.chain machinery, shared verbatim)."""
    from ..analyzer.chain import (
        AdaptiveDispatch, deficit_sized_config, direct_path_chosen,
        donation_enabled, run_bounded_pass, strip_mutable,
    )
    from ..utils.flight_recorder import _NULL_PASS
    flight = flight if flight is not None else _NULL_PASS
    controller = dispatch if dispatch is not None \
        else AdaptiveDispatch(dispatch_rounds, dispatch_target_s)
    donate = donation_enabled(megastep)
    async_rb = bool(megastep.async_readback) if megastep is not None \
        else False
    deficit_cap = megastep.deficit_moves_cap if megastep is not None else 0
    # Direct-assignment mode on the mesh (round 21): the sweep kernels
    # carry the interleaved (rank_stride, block) layout, so each device
    # ranks its LOCAL replica rows into global fill positions
    # rank·shards + device — the per-device plans tile each cell's
    # surplus instead of jointly overshooting it, and the pre-pass runs
    # here exactly as on the single-device bounded path (one dispatch,
    # kind="direct", greedy polish after).
    direct_enabled = bool(megastep is not None
                          and megastep.direct_assignment)
    direct_sweeps_cap = (int(megastep.direct_max_sweeps)
                         if megastep is not None else 16)
    direct_margin = (float(megastep.direct_sparse_margin)
                     if megastep is not None else 0.25)
    direct_seed = sparse_rounding_seed(
        megastep.direct_sparse_salt if megastep is not None else "")
    # Deficit-sized count goals run wide-cost-class rounds (sizing can
    # multiply sources/moves 10-60x), so they get their OWN controller —
    # the single-device path's narrow/wide split: a budget learned on
    # cheap base-width rounds would overshoot the dispatch target by the
    # width ratio on the first sized dispatch, then the halvings would
    # depress the base-width budget, persisted across same-shape passes.
    controller_wide = dispatch_wide if dispatch_wide is not None \
        else (AdaptiveDispatch(dispatch_rounds, dispatch_target_s)
              if deficit_cap > 0 else controller)
    per_goal = {name: [] for name in
                ("viol_before", "obj_before", "offline_before", "viol_after",
                 "obj_after", "offline_after", "moves", "swaps", "rounds")}
    base_kernels = _make_chain_phase_kernels(
        mesh, goals, constraint, cfg, num_topics, presence, swap_moves,
        swap_max_rounds)
    stats_fn = base_kernels[2]
    can_donate = [bool(donate_input)]

    def run_pass(kernels, phase, st, idx, prior, pass_cap: int, ctl,
                 goal_flight):
        move_k, _, _stats_k, move_d, _ = kernels
        # Swap kernels always come from the BASE factory result: the swap
        # bodies close over (swap_moves, swap_max_rounds) only — cfg never
        # reaches them — so a deficit-sized width must not recompile the
        # full-chain sharded swap programs.
        _, swap_k, _, _, swap_d = base_kernels

        def enqueue(st, budget: int):
            b = jnp.int32(budget)
            if donate:
                if not can_donate[0]:
                    # Caller retains the input: donate a sharding-
                    # preserving copy of the two mutable tensors (the
                    # plain-kernel fallback would compile every shard_map
                    # program twice — see chain.optimize_goal_in_chain).
                    st = dataclasses.replace(
                        st, assignment=jnp.copy(st.assignment),
                        leader_slot=jnp.copy(st.leader_slot))
                k = move_d if phase == "move" else swap_d
                a, l, applied, r = k(st.assignment, st.leader_slot,
                                     strip_mutable(st), masks, idx, prior, b)
                st = dataclasses.replace(st, assignment=a, leader_slot=l)
            else:
                k = move_k if phase == "move" else swap_k
                st, applied, r = k(st, masks, idx, prior, b)
            can_donate[0] = True
            return st, applied, r, donate, None

        return run_bounded_pass(enqueue, st, pass_cap, ctl,
                                async_readback=async_rb, stats=stats,
                                kind=phase, flight=goal_flight)

    def run_direct(st, g, goal_flight):
        """Direct-transport pre-pass for goal index ``g``: one sharded
        dispatch, synchronous scalar readback (nothing to pipeline
        behind a single dispatch) — the mesh twin of
        ``direct.run_direct_pass`` with the same donation discipline
        and kind="direct" stats/flight accounting."""
        import time as _time

        from ..utils.sensors import SENSORS
        direct_k, direct_d = _make_direct_phase_kernels(
            mesh, goals, g, constraint, num_topics, presence,
            direct_sweeps_cap, direct_margin, direct_seed)
        t0 = _time.monotonic()
        if donate:
            if not can_donate[0]:
                st = dataclasses.replace(
                    st, assignment=jnp.copy(st.assignment),
                    leader_slot=jnp.copy(st.leader_slot))
            a, l, total, sweeps, planned = direct_d(
                st.assignment, st.leader_slot, strip_mutable(st), masks)
            st = dataclasses.replace(st, assignment=a, leader_slot=l)
            can_donate[0] = True
        else:
            st, total, sweeps, planned = direct_k(st, masks)
        moves = int(total)
        sweeps_run = int(sweeps)
        stranded = int(planned)
        elapsed = _time.monotonic() - t0
        if stats is not None:
            stats.record("direct", sweeps_run, donated=donate)
        goal_flight.dispatch("direct", direct_sweeps_cap, sweeps_run,
                             moves, donated=donate, elapsed_s=elapsed)
        SENSORS.count("solver_direct_sweeps", sweeps_run)
        SENSORS.count("solver_direct_moves", moves)
        SENSORS.count("solver_direct_stranded", stranded)
        return st, moves, sweeps_run, stranded

    for g, goal in enumerate(goals):
        idx = jnp.int32(g)
        prior = jnp.asarray([j < g for j in range(len(goals))])
        viol0, obj0, offline0 = stats_fn(state, masks, idx)
        per_goal["viol_before"].append(float(viol0))
        per_goal["obj_before"].append(float(obj0))
        per_goal["offline_before"].append(int(offline0))
        gf = flight.goal(goal.name)
        gf.entry(violation=float(viol0), objective=float(obj0),
                 offline=int(offline0))
        # The fused kernel's per-goal fast path: zero violations + no
        # offline replicas + no drain pending = skip entirely. Drain
        # pending mirrors _chain_full_local.drain_pending — an alive
        # excluded broker STILL HOSTING replicas, not mere mask presence
        # (presence alone would run every goal on an already-drained
        # cluster that the fused path skips).
        drain = False
        if masks.excluded_replica_move_brokers is not None:
            drain = bool(excluded_hosting_replicas(
                state, masks.excluded_replica_move_brokers).any())
        ran = float(viol0) > 0 or int(offline0) > 0 or drain
        moves_total = swaps_total = rounds = 0
        # Direct-assignment pre-pass (optimize_goal_in_chain semantics):
        # enabled kernel, guard-representable chain prefix, clean model —
        # offline replicas and drains keep the full greedy trajectory.
        use_direct = (direct_enabled and int(offline0) == 0 and not drain
                      and direct_path_chosen(megastep, goal.name)
                      and direct_eligible(goals, g))
        sizing_viol = float(viol0)
        if ran and use_direct and float(viol0) > 0:
            state, d_moves, _d_sweeps, d_stranded = run_direct(state, g, gf)
            moves_total += d_moves
            # Size the greedy POLISH from the larger of two residual
            # estimates (chain.py's post-direct re-size): entry
            # violations minus applied transport moves, and 2x the
            # movers the plan wanted but feasibility refused to place.
            sizing_viol = max(float(viol0) - float(d_moves),
                              2.0 * float(d_stranded))
        # Deficit-aware sizing for count goals (chain.deficit_sized_config
        # semantics): a sized config selects its own phase kernels — the
        # lru_cached factory bounds the compile set to the pow2-quantized
        # widths actually reached.
        cfg_g = cfg
        if deficit_cap > 0 and goal.count_based:
            cfg_g = deficit_sized_config(cfg, sizing_viol, deficit_cap)
            gf.sizing(entry_violation=sizing_viol,
                      base_moves=cfg.moves_per_round,
                      base_sources=cfg.num_sources,
                      sized_moves=cfg_g.moves_per_round,
                      sized_sources=cfg_g.num_sources, cap=deficit_cap)
        gf.grid(cfg_g.num_sources, cfg_g.num_dests, cfg_g.moves_per_round)
        kernels_g = base_kernels if cfg_g is cfg else \
            _make_chain_phase_kernels(mesh, goals, constraint, cfg_g,
                                      num_topics, presence, swap_moves,
                                      swap_max_rounds)
        # Both phases of a sized count goal bill to the wide controller
        # (mirrors the single-device per-goal dispatch= routing).
        ctl_g = controller_wide if (deficit_cap > 0 and goal.count_based) \
            else controller
        if ran:
            while rounds < cfg.max_rounds:
                state, m_, r = run_pass(kernels_g, "move", state, idx,
                                        prior, cfg.max_rounds, ctl_g, gf)
                moves_total += m_
                rounds += r
                if not goal.supports_swap:
                    break
                state, sw, sr = run_pass(kernels_g, "swap", state, idx,
                                         prior, swap_max_rounds, ctl_g, gf)
                swaps_total += sw
                rounds += sr
                if sw == 0:
                    break
            viol1, obj1, offline1 = stats_fn(state, masks, idx)
        else:
            # Skipped goal: state untouched, entry stats ARE exit stats
            # (saves the second stats dispatch per idle goal).
            viol1, obj1, offline1 = viol0, obj0, offline0
        gf.exit(violation=float(viol1), objective=float(obj1),
                offline=int(offline1))
        per_goal["viol_after"].append(float(viol1))
        per_goal["obj_after"].append(float(obj1))
        per_goal["offline_after"].append(int(offline1))
        per_goal["moves"].append(moves_total)
        per_goal["swaps"].append(swaps_total)
        per_goal["rounds"].append(rounds)
    import numpy as np
    # stats_np, not stats: the DispatchStats parameter must stay visible
    # (the unbounded sibling renamed its local to stats_dev for the same
    # reason).
    stats_np = {kname: np.asarray(v) for kname, v in per_goal.items()}
    return state, _chain_infos_from_stats(goals, stats_np)
