"""Multi-chip sharded rebalance search.

SPMD version of ``analyzer.search.optimize_round`` over a 1-D device mesh:

- the partition-indexed tensors (``assignment``, ``leader_slot``, loads,
  ``topic``, ``partition_mask``) are sharded along the mesh axis ``"p"``;
- broker-indexed tensors (capacity, rack, states) are replicated;
- per-broker aggregates (loads, replica/leader counts) are computed as local
  partial segment-sums and combined with ``psum`` — collectives ride ICI;
- every device generates candidates from ITS partitions, scores them against
  the global aggregates, and the small reduced candidate set is
  ``all_gather``-ed so all devices agree on one conflict-free batch;
- each device applies the agreed moves that land in its partition shard.

The scoring body is the SAME code as the single-device round
(search.score_round_candidates / apply_selected) with the psum hook and a
per-shard row offset plugged in — one source of truth for goal semantics.

This replaces the reference's precompute thread pool + shared mutable
ClusterModel (GoalOptimizer.java:112-119, SURVEY.md §2.11) with pure SPMD:
no locks, the "shared state" is the replicated per-broker aggregate.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..analyzer.constraint import BalancingConstraint
from ..analyzer.derived import compute_derived
from ..analyzer.search import (
    ExclusionMasks, OptimizationFailureError, SearchConfig, _conflict_free_top_m,
    apply_selected, goal_aux, reduce_per_source, score_round_candidates,
)
from ..model.tensors import ClusterTensors
from .mesh import PARTITION_AXIS


def _state_specs() -> ClusterTensors:
    """PartitionSpec pytree for ClusterTensors: partition axis sharded,
    broker axis replicated."""
    return ClusterTensors(
        assignment=P(PARTITION_AXIS), leader_slot=P(PARTITION_AXIS),
        leader_load=P(PARTITION_AXIS), follower_load=P(PARTITION_AXIS),
        capacity=P(), rack=P(), broker_state=P(), topic=P(PARTITION_AXIS),
        partition_mask=P(PARTITION_AXIS), broker_mask=P())


def shard_cluster(state: ClusterTensors, mesh: Mesh) -> ClusterTensors:
    """Place a ClusterTensors on the mesh with the partition axis sharded.
    Partition count must divide the mesh size (pad via the builder's
    partition_bucket)."""
    n = mesh.devices.size
    if state.num_partitions % n != 0:
        raise ValueError(
            f"num_partitions {state.num_partitions} not divisible by mesh size {n}")
    specs = _state_specs()
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), state, specs)


def _psum(x):
    return jax.lax.psum(x, PARTITION_AXIS)


def _round_local(state: ClusterTensors, masks: ExclusionMasks, *, goal,
                 optimized, constraint, cfg: SearchConfig, num_topics: int,
                 num_shards: int):
    """Per-device body of one sharded search round (runs under shard_map;
    ``state`` holds this device's partition rows)."""
    shard = jax.lax.axis_index(PARTITION_AXIS)
    p_local = state.num_partitions
    p_global = p_local * num_shards
    offset = shard * p_local

    k_src = max(1, cfg.num_sources // num_shards)
    cand, deltas, score, layout = score_round_candidates(
        state, masks, goal, optimized, constraint, cfg, num_topics,
        psum=_psum, k_src=k_src)

    # Shared per-source reduction; the shard-dependent row offset makes
    # different devices lean toward different destinations among ties.
    red_idx = reduce_per_source(score, layout, row_offset=shard * k_src)

    # Gather every device's reduced candidates (global partition ids) so all
    # devices agree on one conflict-free batch.
    def gather(x):
        return jax.lax.all_gather(x, PARTITION_AXIS).reshape(
            (num_shards * x.shape[0],) + x.shape[1:])

    g_score = gather(score[red_idx])
    g_part = gather(deltas.partition[red_idx] + offset)
    g_src = gather(deltas.src_broker[red_idx])
    g_dst = gather(deltas.dst_broker[red_idx])
    g_slot = gather(deltas.src_slot[red_idx])
    g_dslot = gather(cand.dst_slot[red_idx])
    g_kind = gather(cand.kind[red_idx])

    top_idx, sel = _conflict_free_top_m(g_score, g_part, g_src, g_dst,
                                        cfg.moves_per_round, p_global,
                                        state.num_brokers)

    new_state = apply_selected(state, sel, g_part[top_idx], g_slot[top_idx],
                               g_dst[top_idx], g_kind[top_idx],
                               g_dslot[top_idx], row_offset=offset)
    return new_state, sel.sum()


@lru_cache(maxsize=256)
def _make_sharded_round(mesh: Mesh, goal, optimized, constraint,
                        cfg: SearchConfig, num_topics: int,
                        mask_presence: tuple[bool, bool, bool]):
    """Build + jit the shard_map'd round for one (mesh, goal-chain) config."""
    num_shards = mesh.devices.size
    state_specs = _state_specs()
    body = partial(_round_local, goal=goal, optimized=optimized,
                   constraint=constraint, cfg=cfg, num_topics=num_topics,
                   num_shards=num_shards)
    mapped = shard_map(body, mesh=mesh,
                       in_specs=(state_specs, _mask_specs(mask_presence)),
                       out_specs=(state_specs, P()), check_vma=False)
    return jax.jit(mapped)


def _mask_specs(mask_presence: tuple[bool, bool, bool]) -> ExclusionMasks:
    return ExclusionMasks(
        excluded_topics=P() if mask_presence[0] else None,
        excluded_replica_move_brokers=P() if mask_presence[1] else None,
        excluded_leadership_brokers=P() if mask_presence[2] else None)


@lru_cache(maxsize=256)
def _make_sharded_check(mesh: Mesh, goal, constraint,
                        num_topics: int, mask_presence: tuple[bool, bool, bool]):
    """Total goal violation computed UNDER the mesh (no host gather): psum'd
    derived state + psum'd aux partials, so [T, B]-aux goals never
    materialize on one device."""

    def body(state: ClusterTensors, masks: ExclusionMasks):
        derived = compute_derived(state, masks.excluded_topics,
                                  masks.excluded_replica_move_brokers,
                                  masks.excluded_leadership_brokers, psum=_psum)
        aux = goal_aux(goal, state, derived, constraint, num_topics, psum=_psum)
        viol = goal.broker_violations(state, derived, constraint, aux)
        if goal.partition_additive_scores:
            viol = _psum(viol)
        return viol.sum()

    mapped = shard_map(body, mesh=mesh, in_specs=(_state_specs(),
                                                  _mask_specs(mask_presence)),
                       out_specs=P(), check_vma=False)
    return jax.jit(mapped)


def sharded_optimize_round(state: ClusterTensors, goal, optimized,
                           constraint: BalancingConstraint, cfg: SearchConfig,
                           num_topics: int, masks: ExclusionMasks,
                           mesh: Mesh) -> tuple[ClusterTensors, jax.Array]:
    presence = (masks.excluded_topics is not None,
                masks.excluded_replica_move_brokers is not None,
                masks.excluded_leadership_brokers is not None)
    fn = _make_sharded_round(mesh, goal, tuple(optimized), constraint, cfg,
                             num_topics, presence)
    return fn(state, masks)


def optimize_goal_sharded(state: ClusterTensors, goal, optimized,
                          constraint: BalancingConstraint, cfg: SearchConfig,
                          num_topics: int, mesh: Mesh,
                          masks: ExclusionMasks | None = None,
                          ) -> tuple[ClusterTensors, dict]:
    """Sharded analogue of analyzer.search.optimize_goal: loop rounds until
    no improving action applies; host reads one scalar per round."""
    masks = masks or ExclusionMasks()
    opt_tuple = tuple(optimized)
    total_applied = 0
    total_swaps = 0
    rounds = 0
    for rounds in range(1, cfg.max_rounds + 1):
        state, applied = sharded_optimize_round(
            state, goal, opt_tuple, constraint, cfg, num_topics, masks, mesh)
        applied = int(applied)
        total_applied += applied
        if applied == 0:
            # Swap phase (parity with the single-device optimize_goal): the
            # swap kernel runs as an ordinary jit over the global sharded
            # arrays — XLA inserts the gathers it needs. Swaps are a tail
            # refinement (a handful of rounds), so the gather cost is
            # accepted rather than writing a shard_map swap kernel.
            if goal.supports_swap:
                from ..analyzer.search import swap_round
                state, swapped = swap_round(
                    state, goal, opt_tuple, constraint, num_topics, masks)
                swapped = int(swapped)
                total_swaps += swapped
                total_applied += swapped
                if swapped > 0:
                    continue
            break

    # Final violation check under the mesh — no host gather.
    presence = (masks.excluded_topics is not None,
                masks.excluded_replica_move_brokers is not None,
                masks.excluded_leadership_brokers is not None)
    check = _make_sharded_check(mesh, goal, constraint, num_topics, presence)
    total_violation = float(check(state, masks))
    succeeded = total_violation <= 1e-6
    if goal.is_hard and not succeeded:
        raise OptimizationFailureError(
            f"hard goal {goal.name} unsatisfied: residual violation "
            f"{total_violation:.4f} after {rounds} rounds")
    return state, {
        "goal": goal.name, "rounds": rounds, "moves_applied": total_applied,
        "swaps_applied": total_swaps,
        "residual_violation": total_violation, "succeeded": succeeded,
    }
