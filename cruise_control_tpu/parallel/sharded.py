"""Multi-chip sharded rebalance search.

SPMD version of ``analyzer.search.optimize_round`` over a 1-D device mesh:

- the partition-indexed tensors (``assignment``, ``leader_slot``, loads,
  ``topic``, ``partition_mask``) are sharded along the mesh axis ``"p"``;
- broker-indexed tensors (capacity, rack, states) are replicated;
- per-broker aggregates (loads, replica/leader counts) are computed as local
  partial segment-sums and combined with ``psum`` — collectives ride ICI;
- every device generates candidates from ITS partitions, scores them against
  the global aggregates, and the small reduced candidate set is
  ``all_gather``-ed so all devices agree on one conflict-free batch;
- each device applies the agreed moves that land in its partition shard.

The scoring body is the SAME code as the single-device round
(search.score_round_candidates / apply_selected) with the psum hook and a
per-shard row offset plugged in — one source of truth for goal semantics.

This replaces the reference's precompute thread pool + shared mutable
ClusterModel (GoalOptimizer.java:112-119, SURVEY.md §2.11) with pure SPMD:
no locks, the "shared state" is the replicated per-broker aggregate.
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..analyzer.candidates import Candidates, CandidateDeltas, compute_deltas
from ..analyzer.constraint import BalancingConstraint
from ..analyzer.derived import compute_derived
from ..analyzer.search import (
    _EPS_IMPROVEMENT, ExclusionMasks, OptimizationFailureError, SearchConfig,
    _conflict_free_top_m, _per_broker_top_replicas, apply_selected, goal_aux,
    reduce_per_source, run_rounds_loop, score_round_candidates,
)
from ..model.tensors import ClusterTensors
from .mesh import PARTITION_AXIS, shard_map


def _state_specs() -> ClusterTensors:
    """PartitionSpec pytree for ClusterTensors: partition axis sharded,
    broker axis replicated."""
    return ClusterTensors(
        assignment=P(PARTITION_AXIS), leader_slot=P(PARTITION_AXIS),
        leader_load=P(PARTITION_AXIS), follower_load=P(PARTITION_AXIS),
        capacity=P(), rack=P(), broker_state=P(), topic=P(PARTITION_AXIS),
        partition_mask=P(PARTITION_AXIS), broker_mask=P(), host=P())


def mutable_state_specs() -> tuple:
    """(assignment, leader_slot) specs — the two tensors the search
    mutates, and therefore the EXACT donation set of the donated megastep
    kernels (parallel.chain_sharded): they ride as separate donated
    arguments while everything else travels read-only through
    ``chain.strip_mutable``'s remainder."""
    return P(PARTITION_AXIS), P(PARTITION_AXIS)


def shard_cluster(state: ClusterTensors, mesh: Mesh) -> ClusterTensors:
    """Place a ClusterTensors on the mesh with the partition axis sharded.
    Partition count must divide the mesh size (pad via the builder's
    partition_bucket)."""
    n = mesh.devices.size
    if state.num_partitions % n != 0:
        raise ValueError(
            f"num_partitions {state.num_partitions} not divisible by mesh size {n}")
    specs = _state_specs()
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), state, specs)


def _psum(x):
    return jax.lax.psum(x, PARTITION_AXIS)


def _round_local(state: ClusterTensors, masks: ExclusionMasks, *, goal,
                 optimized, constraint, cfg: SearchConfig, num_topics: int,
                 num_shards: int):
    """Per-device body of one sharded search round (runs under shard_map;
    ``state`` holds this device's partition rows)."""
    shard = jax.lax.axis_index(PARTITION_AXIS)
    p_local = state.num_partitions
    p_global = p_local * num_shards
    offset = shard * p_local

    # Per-device source floor: a too-thin slice (num_sources/shards)
    # can strand the LAST violating replica below a device's top-k
    # while the global single-device search would surface it.
    k_src = max(16, cfg.num_sources // num_shards)
    cand, deltas, score, layout, _ctx = score_round_candidates(
        state, masks, goal, optimized, constraint, cfg, num_topics,
        psum=_psum, k_src=k_src)

    # Shared per-source reduction; the shard-dependent row offset makes
    # different devices lean toward different destinations among ties.
    red_idx = reduce_per_source(score, layout, row_offset=shard * k_src)

    # Gather every device's reduced candidates (global partition ids) so all
    # devices agree on one conflict-free batch.
    def gather(x):
        return jax.lax.all_gather(x, PARTITION_AXIS).reshape(
            (num_shards * x.shape[0],) + x.shape[1:])

    g_score = gather(score[red_idx])
    g_part = gather(deltas.partition[red_idx] + offset)
    g_src = gather(deltas.src_broker[red_idx])
    g_dst = gather(deltas.dst_broker[red_idx])
    g_slot = gather(deltas.src_slot[red_idx])
    g_dslot = gather(cand.dst_slot[red_idx])
    g_kind = gather(cand.kind[red_idx])

    top_idx, sel = _conflict_free_top_m(g_score, g_part, g_src, g_dst,
                                        cfg.moves_per_round, p_global,
                                        state.num_brokers)

    new_state = apply_selected(state, sel, g_part[top_idx], g_slot[top_idx],
                               g_dst[top_idx], g_kind[top_idx],
                               g_dslot[top_idx], row_offset=offset)
    return new_state, sel.sum()


@lru_cache(maxsize=256)
def _make_sharded_round(mesh: Mesh, goal, optimized, constraint,
                        cfg: SearchConfig, num_topics: int,
                        mask_presence: tuple[bool, bool, bool]):
    """Build + jit the shard_map'd round for one (mesh, goal-chain) config."""
    num_shards = mesh.devices.size
    state_specs = _state_specs()
    body = partial(_round_local, goal=goal, optimized=optimized,
                   constraint=constraint, cfg=cfg, num_topics=num_topics,
                   num_shards=num_shards)
    mapped = shard_map(body, mesh=mesh,
                       in_specs=(state_specs, _mask_specs(mask_presence)),
                       out_specs=(state_specs, P()), check_vma=False)
    return jax.jit(mapped)


def _rounds_local(state: ClusterTensors, masks: ExclusionMasks, *, goal,
                  optimized, constraint, cfg: SearchConfig, num_topics: int,
                  num_shards: int):
    """Fused multi-round driver under the mesh: `lax.while_loop` runs
    sharded search rounds (collectives and all) until convergence — ONE
    host round-trip per goal phase instead of one per round (the sharded
    analogue of search.optimize_rounds; VERDICT round 1 weak #3)."""
    return run_rounds_loop(
        lambda s: _round_local(s, masks, goal=goal, optimized=optimized,
                               constraint=constraint, cfg=cfg,
                               num_topics=num_topics, num_shards=num_shards),
        state, cfg.max_rounds)


@lru_cache(maxsize=256)
def _make_sharded_rounds(mesh: Mesh, goal, optimized, constraint,
                         cfg: SearchConfig, num_topics: int,
                         mask_presence: tuple[bool, bool, bool]):
    num_shards = mesh.devices.size
    state_specs = _state_specs()
    body = partial(_rounds_local, goal=goal, optimized=optimized,
                   constraint=constraint, cfg=cfg, num_topics=num_topics,
                   num_shards=num_shards)
    mapped = shard_map(body, mesh=mesh,
                       in_specs=(state_specs, _mask_specs(mask_presence)),
                       out_specs=(state_specs, P(), P()), check_vma=False)
    return jax.jit(mapped)


def _swap_round_local(state: ClusterTensors, masks: ExclusionMasks, *, goal,
                      optimized, constraint, num_topics: int, num_shards: int,
                      k_brokers: int = 8, j_replicas: int = 4,
                      moves: int = 8):
    """One sharded swap round (per-device body).

    The swap phase pairs a heavy replica on an overloaded broker with a
    light replica on a donor broker — the two replicas live on ARBITRARY
    partition shards, so the kernel splits the work (no global gather of
    the model):

    1. LOCAL: each device finds its top-j heaviest/lightest replicas per
       candidate broker and evaluates every prior goal's per-partition LEG
       acceptance against each possible counterparty broker
       (swap_leg_acceptance — partition state is local here).
    2. GATHER: the tiny "replica cards" (weight, load vector, leader flag,
       global id, leg-acceptance bitmaps) are all-gathered — O(K·j·K) per
       device, independent of partition count.
    3. REPLICATED: every device merges the cards (global top-j per broker),
       builds the K×K×j×j pairing grid, applies net acceptance
       (swap_net_acceptance: broker-level by contract) + the active goal's
       net improvement, and selects one conflict-free batch — identical on
       all devices.
    4. LOCAL: each device applies the legs that land in its shard.
    """
    shard = jax.lax.axis_index(PARTITION_AXIS)
    p_local = state.num_partitions
    p_global = p_local * num_shards
    offset = shard * p_local
    b = state.num_brokers
    s_dim = state.max_replication_factor
    j = j_replicas

    derived = compute_derived(state, masks.excluded_topics,
                              masks.excluded_replica_move_brokers,
                              masks.excluded_leadership_brokers, psum=_psum)
    aux = goal_aux(goal, state, derived, constraint, num_topics, psum=_psum)
    aux_by = {g.name: goal_aux(g, state, derived, constraint, num_topics,
                               psum=_psum)
              for g in optimized}

    src_score = goal.source_score(state, derived, constraint, aux)
    if goal.partition_additive_scores:
        src_score = _psum(src_score)
    # Swap counterparties rank by swap_dest_score (broker-indexed, mesh-
    # safe) — consistent with the chain swap bodies. Leg-scored swap
    # IMPROVEMENT overrides still stay single-device (see
    # chain_sharded._chain_swap_local).
    dst_score = goal.swap_dest_score(state, derived, constraint, aux)
    weight = goal.replica_weight(state, derived, constraint, aux)

    k = min(k_brokers, b)
    src_vals, src_brokers = jax.lax.top_k(
        jnp.where(src_score > 0, src_score, -jnp.inf), k)
    dst_vals, dst_brokers = jax.lax.top_k(dst_score, k)
    src_b_ok = jnp.isfinite(src_vals)   # [k], replicated values
    dst_b_ok = jnp.isfinite(dst_vals)

    heavy_idx, heavy_ok = _per_broker_top_replicas(
        state, weight, src_brokers, j, largest=True)     # [k, j] local
    light_idx, light_ok = _per_broker_top_replicas(
        state, weight, dst_brokers, j, largest=False)

    p1, s1 = heavy_idx // s_dim, heavy_idx % s_dim        # local ids [k, j]
    p2, s2 = light_idx // s_dim, light_idx % s_dim

    def leg_masks(pp, ss, ok, counterparties):
        """[k, j, k] leg acceptance: replica (pp, ss) moved to each
        counterparty broker, judged by structural legitimacy + every prior
        goal's swap_leg_acceptance (local partition state)."""
        n = k * j * k
        cand = Candidates(
            kind=jnp.zeros(n, dtype=jnp.int8),
            partition=jnp.broadcast_to(pp[:, :, None], (k, j, k)).reshape(-1),
            src_slot=jnp.broadcast_to(ss[:, :, None], (k, j, k)).reshape(-1),
            dst_broker=jnp.broadcast_to(counterparties[None, None, :],
                                        (k, j, k)).reshape(-1),
            dst_slot=jnp.zeros(n, dtype=jnp.int32),
            valid=jnp.broadcast_to(ok[:, :, None], (k, j, k)).reshape(-1))
        d = compute_deltas(state, derived, cand)
        acc = d.valid
        for g in optimized:
            acc &= g.swap_leg_acceptance(state, derived, constraint,
                                         aux_by[g.name], d)
        return acc.reshape(k, j, k)

    leg_f = leg_masks(p1, s1, heavy_ok, dst_brokers)   # heavy → dst brokers
    leg_r = leg_masks(p2, s2, light_ok, src_brokers)   # light → src brokers

    # Replica cards. Invalid heavy cards sink (-inf), invalid light float
    # (+inf) so the global top-j merge never picks them.
    w_a = jnp.where(heavy_ok, weight[p1, s1], -jnp.inf)
    w_b = jnp.where(light_ok, weight[p2, s2], jnp.inf)
    lead1 = state.leader_slot[p1] == s1
    lead2 = state.leader_slot[p2] == s2
    load_a = jnp.where(lead1[..., None], state.leader_load[p1],
                       state.follower_load[p1])          # [k, j, R]
    load_b = jnp.where(lead2[..., None], state.leader_load[p2],
                       state.follower_load[p2])
    gp1, gp2 = p1 + offset, p2 + offset
    top1 = state.topic[p1]

    def gather_cards(x):
        """[k, j, ...] per-device → [k, num_shards·j, ...] merged."""
        y = jax.lax.all_gather(x, PARTITION_AXIS)        # [n_sh, k, j, ...]
        y = jnp.moveaxis(y, 0, 1)                        # [k, n_sh, j, ...]
        return y.reshape((k, num_shards * j) + y.shape[3:])

    g_wa = gather_cards(w_a)
    g_wb = gather_cards(w_b)
    hv, hsel = jax.lax.top_k(g_wa, j)                    # global top-j heavy
    lv, lsel = jax.lax.top_k(-g_wb, j)                   # global top-j light
    heavy_ok_g = jnp.isfinite(hv)
    light_ok_g = jnp.isfinite(lv)

    def pick(gathered, sel):
        extra = gathered.ndim - 2
        return jnp.take_along_axis(
            gathered, sel.reshape(sel.shape + (1,) * extra), axis=1)

    h_load = pick(gather_cards(load_a), hsel)            # [k, j, R]
    l_load = pick(gather_cards(load_b), lsel)
    h_lead = pick(gather_cards(lead1), hsel)
    l_lead = pick(gather_cards(lead2), lsel)
    h_gp = pick(gather_cards(gp1), hsel)
    l_gp = pick(gather_cards(gp2), lsel)
    h_s = pick(gather_cards(s1), hsel)
    l_s = pick(gather_cards(s2), lsel)
    h_topic = pick(gather_cards(top1), hsel)
    h_legs = pick(gather_cards(leg_f), hsel)             # [k, j, k]
    l_legs = pick(gather_cards(leg_r), lsel)
    h_w = hv          # top_k values of g_wa
    l_w = -lv         # top_k of -g_wb ⇒ negate back

    # Pairing grid [k_src, k_dst, j, j] — replicated, identical everywhere.
    n = k * k * j * j
    si, di, ai, bi = jnp.meshgrid(jnp.arange(k), jnp.arange(k),
                                  jnp.arange(j), jnp.arange(j), indexing="ij")
    si, di, ai, bi = (x.reshape(-1) for x in (si, di, ai, bi))
    src_b = src_brokers[si]
    dst_b = dst_brokers[di]
    wa = h_w[si, ai]
    wb = l_w[di, bi]
    sel_gp1 = h_gp[si, ai]
    sel_gp2 = l_gp[di, bi]

    base_valid = src_b_ok[si] & dst_b_ok[di] & heavy_ok_g[si, ai] \
        & light_ok_g[di, bi] & (src_b != dst_b) & (sel_gp1 != sel_gp2) \
        & (wa > wb) & h_legs[si, ai, di] & l_legs[di, bi, si]

    lead_d = h_lead[si, ai].astype(jnp.int32) - l_lead[di, bi].astype(jnp.int32)
    net_load = h_load[si, ai] - l_load[di, bi]
    net = CandidateDeltas(
        src_broker=jnp.where(base_valid, src_b, 0),
        dst_broker=jnp.where(base_valid, dst_b, 0),
        load_delta=jnp.where(base_valid[:, None], net_load, 0.0),
        replica_delta=jnp.zeros(n, dtype=jnp.int32),
        leader_delta=jnp.where(base_valid, lead_d, 0),
        partition=sel_gp1, topic=h_topic[si, ai],
        src_slot=h_s[si, ai], dst_slot=jnp.zeros(n, dtype=jnp.int32),
        valid=base_valid)

    accept = base_valid
    for g in optimized:
        accept &= g.swap_net_acceptance(state, derived, constraint,
                                        aux_by[g.name], net)
    imp = goal.improvement(state, derived, constraint, aux, net)
    score = jnp.where(accept, imp, -jnp.inf)

    # Conflict-free selection over GLOBAL partition/broker key spaces —
    # replicated and deterministic (same inputs on every device).
    k_m = min(moves, n)
    top_score, top_idx = jax.lax.top_k(score, k_m)
    ok = top_score > _EPS_IMPROVEMENT
    rank = jnp.arange(k_m, dtype=jnp.int32)
    big = jnp.int32(k_m + 1)
    rank_eff = jnp.where(ok, rank, big)
    t_gp1, t_gp2 = sel_gp1[top_idx], sel_gp2[top_idx]
    t_src, t_dst = src_b[top_idx], dst_b[top_idx]
    first_part = jnp.full(p_global, big, jnp.int32) \
        .at[t_gp1].min(rank_eff).at[t_gp2].min(rank_eff)
    first_broker = jnp.full(b, big, jnp.int32) \
        .at[t_src].min(rank_eff).at[t_dst].min(rank_eff)
    sel = ok & (first_part[t_gp1] == rank) & (first_part[t_gp2] == rank) \
        & (first_broker[t_src] == rank) & (first_broker[t_dst] == rank)

    # Apply the legs owned by this shard (OOB rows drop).
    p_pad = jnp.int32(p_local)
    row1 = t_gp1 - offset
    row2 = t_gp2 - offset
    rows1 = jnp.where(sel & (row1 >= 0) & (row1 < p_local), row1, p_pad)
    rows2 = jnp.where(sel & (row2 >= 0) & (row2 < p_local), row2, p_pad)
    new_assignment = state.assignment \
        .at[rows1, h_s[si, ai][top_idx]].set(
            t_dst.astype(state.assignment.dtype), mode="drop") \
        .at[rows2, l_s[di, bi][top_idx]].set(
            t_src.astype(state.assignment.dtype), mode="drop")
    return dataclasses.replace(state, assignment=new_assignment), sel.sum()


def _swap_rounds_local(state: ClusterTensors, masks: ExclusionMasks, *, goal,
                       optimized, constraint, num_topics: int,
                       num_shards: int, moves: int = 8, max_rounds: int = 64):
    """Fused sharded swap driver (while_loop analogue of swap_rounds)."""
    return run_rounds_loop(
        lambda s: _swap_round_local(
            s, masks, goal=goal, optimized=optimized, constraint=constraint,
            num_topics=num_topics, num_shards=num_shards, moves=moves),
        state, max_rounds)


@lru_cache(maxsize=256)
def _make_sharded_swap_rounds(mesh: Mesh, goal, optimized, constraint,
                              num_topics: int,
                              mask_presence: tuple[bool, bool, bool]):
    num_shards = mesh.devices.size
    state_specs = _state_specs()
    body = partial(_swap_rounds_local, goal=goal, optimized=optimized,
                   constraint=constraint, num_topics=num_topics,
                   num_shards=num_shards)
    mapped = shard_map(body, mesh=mesh,
                       in_specs=(state_specs, _mask_specs(mask_presence)),
                       out_specs=(state_specs, P(), P()), check_vma=False)
    return jax.jit(mapped)


def _mask_specs(mask_presence: tuple[bool, bool, bool]) -> ExclusionMasks:
    return ExclusionMasks(
        excluded_topics=P() if mask_presence[0] else None,
        excluded_replica_move_brokers=P() if mask_presence[1] else None,
        excluded_leadership_brokers=P() if mask_presence[2] else None)


@lru_cache(maxsize=256)
def _make_sharded_check(mesh: Mesh, goal, constraint,
                        num_topics: int, mask_presence: tuple[bool, bool, bool]):
    """Total goal violation computed UNDER the mesh (no host gather): psum'd
    derived state + psum'd aux partials, so [T, B]-aux goals never
    materialize on one device."""

    def body(state: ClusterTensors, masks: ExclusionMasks):
        derived = compute_derived(state, masks.excluded_topics,
                                  masks.excluded_replica_move_brokers,
                                  masks.excluded_leadership_brokers, psum=_psum)
        aux = goal_aux(goal, state, derived, constraint, num_topics, psum=_psum)
        viol = goal.broker_violations(state, derived, constraint, aux)
        if goal.partition_additive_scores:
            viol = _psum(viol)
        return viol.sum()

    mapped = shard_map(body, mesh=mesh, in_specs=(_state_specs(),
                                                  _mask_specs(mask_presence)),
                       out_specs=P(), check_vma=False)
    return jax.jit(mapped)


def sharded_optimize_round(state: ClusterTensors, goal, optimized,
                           constraint: BalancingConstraint, cfg: SearchConfig,
                           num_topics: int, masks: ExclusionMasks,
                           mesh: Mesh) -> tuple[ClusterTensors, jax.Array]:
    presence = (masks.excluded_topics is not None,
                masks.excluded_replica_move_brokers is not None,
                masks.excluded_leadership_brokers is not None)
    fn = _make_sharded_round(mesh, goal, tuple(optimized), constraint, cfg,
                             num_topics, presence)
    return fn(state, masks)


@lru_cache(maxsize=256)
def _make_sharded_swap_round(mesh: Mesh, goal, optimized, constraint,
                             num_topics: int,
                             mask_presence: tuple[bool, bool, bool]):
    num_shards = mesh.devices.size
    body = partial(_swap_round_local, goal=goal, optimized=optimized,
                   constraint=constraint, num_topics=num_topics,
                   num_shards=num_shards)
    mapped = shard_map(body, mesh=mesh,
                       in_specs=(_state_specs(), _mask_specs(mask_presence)),
                       out_specs=(_state_specs(), P()), check_vma=False)
    return jax.jit(mapped)


def sharded_swap_round(state: ClusterTensors, goal, optimized,
                       constraint: BalancingConstraint, num_topics: int,
                       masks: ExclusionMasks, mesh: Mesh,
                       ) -> tuple[ClusterTensors, jax.Array]:
    """One sharded swap round (card-gather kernel; see _swap_round_local)."""
    presence = (masks.excluded_topics is not None,
                masks.excluded_replica_move_brokers is not None,
                masks.excluded_leadership_brokers is not None)
    fn = _make_sharded_swap_round(mesh, goal, tuple(optimized), constraint,
                                  num_topics, presence)
    return fn(state, masks)


def optimize_goal_sharded(state: ClusterTensors, goal, optimized,
                          constraint: BalancingConstraint, cfg: SearchConfig,
                          num_topics: int, mesh: Mesh,
                          masks: ExclusionMasks | None = None,
                          ) -> tuple[ClusterTensors, dict]:
    """Sharded analogue of analyzer.search.optimize_goal.

    Both the move loop and the swap loop run as FUSED `lax.while_loop`
    drivers under the mesh — the host reads back one scalar per PHASE
    (``host_roundtrips`` in the info dict), not one per round, matching the
    single-chip path's dispatch profile over a high-latency device link."""
    masks = masks or ExclusionMasks()
    opt_tuple = tuple(optimized)
    presence = (masks.excluded_topics is not None,
                masks.excluded_replica_move_brokers is not None,
                masks.excluded_leadership_brokers is not None)
    fn_rounds = _make_sharded_rounds(mesh, goal, opt_tuple, constraint, cfg,
                                     num_topics, presence)
    fn_swaps = _make_sharded_swap_rounds(mesh, goal, opt_tuple, constraint,
                                         num_topics, presence) \
        if goal.supports_swap else None

    total_applied = 0
    total_swaps = 0
    rounds = 0
    roundtrips = 0
    while rounds < cfg.max_rounds:
        state, moves, r = fn_rounds(state, masks)
        roundtrips += 1
        total_applied += int(moves)
        rounds += int(r)
        if fn_swaps is None:
            break
        state, swapped, sr = fn_swaps(state, masks)
        roundtrips += 1
        swapped = int(swapped)
        total_swaps += swapped
        total_applied += swapped
        rounds += int(sr)
        if swapped == 0:
            break

    # Final violation check under the mesh — no host gather.
    check = _make_sharded_check(mesh, goal, constraint, num_topics, presence)
    total_violation = float(check(state, masks))
    roundtrips += 1
    succeeded = total_violation <= 1e-6
    if goal.is_hard and not succeeded:
        raise OptimizationFailureError(
            f"hard goal {goal.name} unsatisfied: residual violation "
            f"{total_violation:.4f} after {rounds} rounds")
    return state, {
        "goal": goal.name, "rounds": rounds, "moves_applied": total_applied,
        "swaps_applied": total_swaps,
        "residual_violation": total_violation, "succeeded": succeeded,
        "host_roundtrips": roundtrips,
    }
