"""Multi-host solver initialization (jax.distributed over ICI/DCN).

The reference scales its optimizer with an in-JVM thread pool
(GoalOptimizer.java:112-119) and talks to the outside world over
Kafka/ZooKeeper RPC (SURVEY.md §2.11). The TPU-native equivalent runs ONE
SPMD program over a pod slice: each host process owns its local chips,
``jax.distributed.initialize`` wires the processes into a single runtime,
and the solver mesh spans every device — collectives ride ICI within a
slice and DCN across slices. No hand-rolled RPC: the sharded kernels in
``sharded.py`` are topology-agnostic (they see one mesh).

Usage (one process per host, e.g. under GKE/ray/mpi):

    from cruise_control_tpu.parallel import distributed
    distributed.initialize()            # env-driven (TPU pods auto-detect)
    mesh = distributed.global_mesh()    # 1-D mesh over ALL devices
    sharded = shard_cluster(state, mesh)  # global arrays, per-host shards

On a TPU pod slice, ``initialize()`` needs no arguments — the TPU runtime
supplies coordinator address, process count and process id. Elsewhere pass
them explicitly or via JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
JAX_PROCESS_ID.
"""

from __future__ import annotations

import os
import warnings

import jax
import numpy as np
from jax.sharding import Mesh

from .mesh import PARTITION_AXIS

_initialized = False


# Env markers a TPU pod / multislice runtime sets on worker hosts —
# checkable WITHOUT touching the XLA backend (jax.distributed.initialize
# must run before any backend use, so probing jax.devices()/process_count()
# here would make multi-host init impossible).
_POD_ENV_MARKERS = ("TPU_WORKER_HOSTNAMES", "TPU_WORKER_ID",
                    "MEGASCALE_COORDINATOR_ADDRESS", "CLOUD_TPU_TASK_ID")


def _backend_initialized() -> bool:
    from jax._src import xla_bridge
    probe = getattr(xla_bridge, "backends_are_initialized", None)
    return bool(probe()) if probe is not None else False


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> None:
    """Join this process into the multi-host JAX runtime (idempotent).

    MUST run before any JAX call that initializes the XLA backend. The
    decision to join is made purely from arguments and environment
    variables for the same reason. Single-process deployments may skip
    this entirely; with no explicit configuration and no pod environment
    markers it is a no-op.
    """
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    env_np = os.environ.get("JAX_NUM_PROCESSES")
    env_pid = os.environ.get("JAX_PROCESS_ID")
    num_processes = num_processes if num_processes is not None else (
        int(env_np) if env_np else None)
    process_id = process_id if process_id is not None else (
        int(env_pid) if env_pid else None)

    explicit = coordinator_address is not None or num_processes is not None \
        or process_id is not None
    on_pod = any(os.environ.get(m) for m in _POD_ENV_MARKERS)
    if not explicit and not on_pod:
        return  # single-host run; nothing to join
    if _backend_initialized():
        if not explicit:
            # Pod env markers alone are not a request for multi-host init —
            # single-host TPU VMs carry them too. A library user who touched
            # JAX first gets a warning and a single-process runtime, not a
            # crash.
            # NOT latched as initialized: a later explicit
            # initialize(coordinator_address=...) must still raise loudly
            # rather than silently no-op on the idempotency check.
            warnings.warn(
                "parallel.distributed.initialize(): XLA backend already "
                "initialized and no explicit multi-host configuration was "
                "given — continuing single-process. To join a multi-host "
                "runtime, call initialize() before any jax computation.",
                RuntimeWarning, stacklevel=2)
            return
        raise RuntimeError(
            "parallel.distributed.initialize() called after the XLA backend "
            "was already initialized — call it before any jax computation "
            "or device query in this process.")
    if explicit:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
    else:
        jax.distributed.initialize()  # TPU pod runtime auto-detects
    _initialized = True


def global_mesh() -> Mesh:
    """1-D solver mesh over every device in the (possibly multi-host)
    runtime. With ``jax.distributed`` initialized, ``jax.devices()`` lists
    ALL devices across hosts; each host addresses only its local shards and
    the sharded kernels' psum/all_gather ride ICI/DCN."""
    return Mesh(np.asarray(jax.devices()), (PARTITION_AXIS,))


def process_info() -> dict:
    """Diagnostic snapshot for the STATE endpoint / logs."""
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
        "backend": jax.default_backend(),
    }
