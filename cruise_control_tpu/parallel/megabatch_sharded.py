"""Device-sharded megabatch: the fleet's CLUSTER axis on the mesh.

Round 14 solves a whole bucket of clusters in ONE donated program on one
device; this module grows that cluster dimension onto the 1-D device
mesh (ROADMAP item 3, the Podracer/Anakin + Brax idiom already cited
in-tree: keep loops on-device, batch everything through one program
across the mesh). Each megabatch driver — move, swap, direct transport,
goal stats — gets a ``shard_map`` twin that places
``batch_width / n_devices`` cluster slots per device:

- EVERY stacked field shards along the leading cluster axis (unlike the
  partition-axis solver in ``parallel/sharded.py``, there are no
  replicated topology planes here — ``stack_states`` stacks the whole
  pytree, so capacity/rack/broker planes carry the cluster axis too);
- clusters are INDEPENDENT, so the per-device body is literally the
  single-device batched driver at local width and there are NO
  collectives — each device's ``lax.while_loop`` early-exits on its OWN
  clusters' ``active.any()``, which is the scaling win: a device whose
  shard converged goes idle instead of spinning frozen-select rounds
  until the slowest cluster fleet-wide finishes;
- the one-behind pump (``chain.run_megabatch_pass``) is unchanged: the
  per-cluster early-exit mask chains dispatch-to-dispatch as a sharded
  device value, exactly like the state.

Byte parity per cluster against the single-device megabatch is the
correctness contract (tests/test_megabatch_sharded.py pins it at two
bucket shapes x two occupancies): the freeze-select discipline makes a
cluster's trajectory depend only on its own rows and the shared global
round index, so splitting the batch across devices — each running the
same rounds until ITS shard converges — changes nothing per cluster.
Inert pad slots (``chain.inert_state_like``) shard along the same axis
and stay byte-frozen; pad-to-device-multiple is the optimizer's job
(``optimizations_megabatch`` rounds the batch width up, the same
append-only padding soundness as ``fleet/bucketing.py``).

Donation contract (CCSA002): identical to the single-device donated
twins — the batched mutable pair ``{assignment[C,P,S],
leader_slot[C,P]}`` rides as two separately-donated sharded arguments
and the stacked remainder travels read-only with zero-row placeholders.
``jnp.copy`` preserves sharding, so the chain layer's copy-on-first-
dispatch donation guard works unchanged on sharded inputs.
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..analyzer.search import ExclusionMasks
from ..model.tensors import ClusterTensors
from .mesh import PARTITION_AXIS, shard_map

# The fleet mesh is the solver mesh: one 1-D axis. For the megabatch
# twins that axis carries CLUSTERS (each device holds whole clusters),
# not partition rows — same mesh object, different sharded dimension.
CLUSTER_AXIS = PARTITION_AXIS


def cluster_state_specs() -> ClusterTensors:
    """PartitionSpec pytree for a STACKED ClusterTensors: every field
    leads with the cluster axis (``stack_states`` stacks the whole
    pytree), so every field shards along the mesh."""
    c = P(CLUSTER_AXIS)
    return ClusterTensors(
        assignment=c, leader_slot=c, leader_load=c, follower_load=c,
        capacity=c, rack=c, broker_state=c, topic=c, partition_mask=c,
        broker_mask=c, host=c)


def megabatch_mask_specs(
        mask_presence: tuple[bool, bool, bool]) -> ExclusionMasks:
    """Specs for the stacked exclusion masks: present fields carry the
    cluster axis (the optimizer stacks one mask row per cluster)."""
    c = P(CLUSTER_AXIS)
    return ExclusionMasks(
        excluded_topics=c if mask_presence[0] else None,
        excluded_replica_move_brokers=c if mask_presence[1] else None,
        excluded_leadership_brokers=c if mask_presence[2] else None)


def masks_presence(masks: ExclusionMasks) -> tuple[bool, bool, bool]:
    return (masks.excluded_topics is not None,
            masks.excluded_replica_move_brokers is not None,
            masks.excluded_leadership_brokers is not None)


def shard_megabatch(batched: ClusterTensors, mesh: Mesh) -> ClusterTensors:
    """Place a stacked megabatch on the mesh, cluster axis sharded. The
    batch width must divide the mesh (the optimizer pads it to a device
    multiple before stacking)."""
    n = mesh.devices.size
    c = batched.assignment.shape[0]
    if c % n != 0:
        raise ValueError(
            f"megabatch width {c} not divisible by mesh size {n}")
    specs = cluster_state_specs()
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), batched,
        specs)


def shard_megabatch_masks(masks: ExclusionMasks,
                          mesh: Mesh) -> ExclusionMasks:
    """Place the stacked mask fields on the mesh (None fields stay
    None)."""
    sh = NamedSharding(mesh, P(CLUSTER_AXIS))
    return ExclusionMasks(*(
        None if f is None else jax.device_put(f, sh)
        for f in (masks.excluded_topics,
                  masks.excluded_replica_move_brokers,
                  masks.excluded_leadership_brokers)))


@lru_cache(maxsize=64)
def _make_move_kernels(mesh: Mesh, goals, constraint, cfg, num_topics: int,
                       mask_presence: tuple[bool, bool, bool],
                       ring_rounds: int):
    """Sharded move-megastep pair (plain, donated): the per-device body
    IS ``chain._megabatch_rounds_driver`` at local width — no
    collectives, per-device early exit."""
    from ..analyzer.chain import _megabatch_rounds_driver
    rep = P()
    cs = P(CLUSTER_AXIS)
    state_specs = cluster_state_specs()
    mask_specs = megabatch_mask_specs(mask_presence)
    ring_spec = cs if ring_rounds > 0 else None

    def body(states, active0, masks, active_idx, prior_mask, budget):
        return _megabatch_rounds_driver(
            states, active0, active_idx, prior_mask, goals, constraint,
            cfg, num_topics, masks, budget, ring_rounds=ring_rounds)

    def move_body_donated(assignment, leader_slot, rest, active0, masks,
                          active_idx, prior_mask, budget):
        states = dataclasses.replace(rest, assignment=assignment,
                                     leader_slot=leader_slot)
        final, total, rounds, active, ring = _megabatch_rounds_driver(
            states, active0, active_idx, prior_mask, goals, constraint,
            cfg, num_topics, masks, budget, ring_rounds=ring_rounds)
        return (final.assignment, final.leader_slot, total, rounds,
                active, ring)

    move = jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(state_specs, cs, mask_specs, rep, rep, rep),
        out_specs=(state_specs, cs, cs, cs, ring_spec), check_vma=False))
    move_d = jax.jit(shard_map(
        move_body_donated, mesh=mesh,
        in_specs=(cs, cs, state_specs, cs, mask_specs, rep, rep, rep),
        out_specs=(cs, cs, cs, cs, cs, ring_spec), check_vma=False),
        donate_argnums=(0, 1))
    return move, move_d


@lru_cache(maxsize=64)
def _make_swap_kernels(mesh: Mesh, goals, constraint, num_topics: int,
                       mask_presence: tuple[bool, bool, bool], moves: int,
                       max_rounds: int):
    """Sharded swap-megastep pair (plain, donated)."""
    from ..analyzer.chain import _megabatch_swap_driver
    rep = P()
    cs = P(CLUSTER_AXIS)
    state_specs = cluster_state_specs()
    mask_specs = megabatch_mask_specs(mask_presence)

    def body(states, active0, masks, active_idx, prior_mask, budget):
        return _megabatch_swap_driver(
            states, active0, active_idx, prior_mask, goals, constraint,
            num_topics, masks, moves, max_rounds, budget)

    def swap_body_donated(assignment, leader_slot, rest, active0, masks,
                          active_idx, prior_mask, budget):
        states = dataclasses.replace(rest, assignment=assignment,
                                     leader_slot=leader_slot)
        final, total, rounds, active = _megabatch_swap_driver(
            states, active0, active_idx, prior_mask, goals, constraint,
            num_topics, masks, moves, max_rounds, budget)
        return final.assignment, final.leader_slot, total, rounds, active

    swap = jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(state_specs, cs, mask_specs, rep, rep, rep),
        out_specs=(state_specs, cs, cs, cs), check_vma=False))
    swap_d = jax.jit(shard_map(
        swap_body_donated, mesh=mesh,
        in_specs=(cs, cs, state_specs, cs, mask_specs, rep, rep, rep),
        out_specs=(cs, cs, cs, cs, cs), check_vma=False),
        donate_argnums=(0, 1))
    return swap, swap_d


@lru_cache(maxsize=64)
def _make_direct_kernels(mesh: Mesh, goals, index: int, constraint,
                         num_topics: int,
                         mask_presence: tuple[bool, bool, bool],
                         max_sweeps: int, margin_frac: float, seed: int):
    """Sharded direct-transport pair for ONE goal index (the megabatch
    freeze-discipline sweep loop of ``analyzer.direct``, per-device at
    local width). Like the single-device twin, the sweep body is
    selected by trace-time dispatch on the goal index, so the kernel is
    built per-(mesh, index) — the lru_cache bounds the set to the
    direct-eligible count goals actually reached."""
    from ..analyzer.direct import _megabatch_direct_driver
    cs = P(CLUSTER_AXIS)
    state_specs = cluster_state_specs()
    mask_specs = megabatch_mask_specs(mask_presence)

    def body(states, active0, masks):
        return _megabatch_direct_driver(
            states, active0, goals, index, constraint, num_topics, masks,
            max_sweeps, margin_frac=margin_frac, seed=seed)

    def direct_body_donated(assignment, leader_slot, rest, active0, masks):
        states = dataclasses.replace(rest, assignment=assignment,
                                     leader_slot=leader_slot)
        final, total, sweeps, active = _megabatch_direct_driver(
            states, active0, goals, index, constraint, num_topics, masks,
            max_sweeps, margin_frac=margin_frac, seed=seed)
        return final.assignment, final.leader_slot, total, sweeps, active

    direct = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(state_specs, cs, mask_specs),
        out_specs=(state_specs, cs, cs, cs), check_vma=False))
    direct_d = jax.jit(shard_map(
        direct_body_donated, mesh=mesh,
        in_specs=(cs, cs, state_specs, cs, mask_specs),
        out_specs=(cs, cs, cs, cs, cs), check_vma=False),
        donate_argnums=(0, 1))
    return direct, direct_d


@lru_cache(maxsize=64)
def _make_stats_kernels(mesh: Mesh, goals, constraint, num_topics: int,
                        mask_presence: tuple[bool, bool, bool]):
    """Sharded (per-goal stats, all-goal stats) pair — the entry/exit
    and fingerprint-snapshot programs on the sharded cluster axis."""
    from ..analyzer.chain import (
        _chain_all_goal_stats_body, _chain_goal_stats_body, _mask_axes,
    )
    rep = P()
    cs = P(CLUSTER_AXIS)
    state_specs = cluster_state_specs()
    mask_specs = megabatch_mask_specs(mask_presence)

    def stats_body(states, masks, active_idx):
        mask_fields, mask_ax = _mask_axes(masks)

        def per_cluster(s, tm, rm, lm):
            return _chain_goal_stats_body(s, active_idx, goals, constraint,
                                          num_topics,
                                          ExclusionMasks(tm, rm, lm))

        return jax.vmap(per_cluster, in_axes=(0,) + mask_ax)(states,
                                                             *mask_fields)

    def all_stats_body(states, masks):
        mask_fields, mask_ax = _mask_axes(masks)

        def per_cluster(s, tm, rm, lm):
            return _chain_all_goal_stats_body(s, goals, constraint,
                                              num_topics,
                                              ExclusionMasks(tm, rm, lm))

        return jax.vmap(per_cluster, in_axes=(0,) + mask_ax)(states,
                                                             *mask_fields)

    stats = jax.jit(shard_map(
        stats_body, mesh=mesh, in_specs=(state_specs, mask_specs, rep),
        out_specs=(cs, cs, cs), check_vma=False))
    all_stats = jax.jit(shard_map(
        all_stats_body, mesh=mesh, in_specs=(state_specs, mask_specs),
        out_specs=(cs, cs, cs), check_vma=False))
    return stats, all_stats


# ---------------------------------------------------------------------------
# Call-compatible wrappers: the chain layer swaps these in for the
# single-device jitted kernels (same argument order, leading mesh) so
# make_enqueue / the direct pre-pass / the stats readbacks stay
# single-path.
# ---------------------------------------------------------------------------

def megabatch_optimize_rounds_sharded(mesh: Mesh, states, active0,
                                      active_idx, prior_mask, goals,
                                      constraint, cfg, num_topics: int,
                                      masks, budget, ring_rounds: int = 0):
    """Sharded twin of ``chain.megabatch_optimize_rounds``."""
    move, _ = _make_move_kernels(mesh, goals, constraint, cfg, num_topics,
                                 masks_presence(masks), ring_rounds)
    final, total, rounds, active, ring = move(
        states, active0, masks, jnp.int32(active_idx), prior_mask,
        jnp.int32(budget))
    if ring_rounds > 0:
        return final, total, rounds, active, ring
    return final, total, rounds, active


def megabatch_optimize_rounds_donated_sharded(mesh: Mesh, assignment,
                                              leader_slot, rest, active0,
                                              active_idx, prior_mask, goals,
                                              constraint, cfg,
                                              num_topics: int, masks,
                                              budget, ring_rounds: int = 0):
    """Sharded twin of ``chain.megabatch_optimize_rounds_donated``."""
    _, move_d = _make_move_kernels(mesh, goals, constraint, cfg,
                                   num_topics, masks_presence(masks),
                                   ring_rounds)
    a, l, total, rounds, active, ring = move_d(
        assignment, leader_slot, rest, active0, masks,
        jnp.int32(active_idx), prior_mask, jnp.int32(budget))
    if ring_rounds > 0:
        return a, l, total, rounds, active, ring
    return a, l, total, rounds, active


def megabatch_swap_rounds_sharded(mesh: Mesh, states, active0, active_idx,
                                  prior_mask, goals, constraint,
                                  num_topics: int, masks, moves: int,
                                  max_rounds: int, budget):
    """Sharded twin of ``chain.megabatch_swap_rounds``."""
    swap, _ = _make_swap_kernels(mesh, goals, constraint, num_topics,
                                 masks_presence(masks), moves, max_rounds)
    return swap(states, active0, masks, jnp.int32(active_idx), prior_mask,
                jnp.int32(budget))


def megabatch_swap_rounds_donated_sharded(mesh: Mesh, assignment,
                                          leader_slot, rest, active0,
                                          active_idx, prior_mask, goals,
                                          constraint, num_topics: int,
                                          masks, moves: int,
                                          max_rounds: int, budget):
    """Sharded twin of ``chain.megabatch_swap_rounds_donated``."""
    _, swap_d = _make_swap_kernels(mesh, goals, constraint, num_topics,
                                   masks_presence(masks), moves,
                                   max_rounds)
    return swap_d(assignment, leader_slot, rest, active0, masks,
                  jnp.int32(active_idx), prior_mask, jnp.int32(budget))


def megabatch_direct_rounds_sharded(mesh: Mesh, states, active0, goals,
                                    index: int, constraint,
                                    num_topics: int, masks,
                                    max_sweeps: int = 8,
                                    margin_frac: float = 0.25,
                                    seed: int | None = None):
    """Sharded twin of ``direct.megabatch_direct_rounds``."""
    from ..analyzer.direct import SPARSE_ROUNDING_SEED
    direct, _ = _make_direct_kernels(
        mesh, goals, index, constraint, num_topics, masks_presence(masks),
        max_sweeps, margin_frac,
        SPARSE_ROUNDING_SEED if seed is None else seed)
    return direct(states, active0, masks)


def megabatch_direct_rounds_donated_sharded(mesh: Mesh, assignment,
                                            leader_slot, rest, active0,
                                            goals, index: int, constraint,
                                            num_topics: int, masks,
                                            max_sweeps: int = 8,
                                            margin_frac: float = 0.25,
                                            seed: int | None = None):
    """Sharded twin of ``direct.megabatch_direct_rounds_donated``."""
    from ..analyzer.direct import SPARSE_ROUNDING_SEED
    _, direct_d = _make_direct_kernels(
        mesh, goals, index, constraint, num_topics, masks_presence(masks),
        max_sweeps, margin_frac,
        SPARSE_ROUNDING_SEED if seed is None else seed)
    return direct_d(assignment, leader_slot, rest, active0, masks)


def megabatch_goal_stats_sharded(mesh: Mesh, states, active_idx, goals,
                                 constraint, num_topics: int, masks):
    """Sharded twin of ``chain.megabatch_goal_stats``."""
    stats, _ = _make_stats_kernels(mesh, goals, constraint, num_topics,
                                   masks_presence(masks))
    return stats(states, masks, jnp.int32(active_idx))


def megabatch_all_goal_stats_sharded(mesh: Mesh, states, goals, constraint,
                                     num_topics: int, masks):
    """Sharded twin of ``chain.megabatch_all_goal_stats`` (the
    fingerprint-skip snapshot)."""
    _, all_stats = _make_stats_kernels(mesh, goals, constraint, num_topics,
                                       masks_presence(masks))
    return all_stats(states, masks)
