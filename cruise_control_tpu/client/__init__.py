"""Console client (reference: cruise-control-client/ — cccli, Responder)."""

from .cccli import build_parser, main
from .responder import CruiseControlClientError, Responder

__all__ = ["build_parser", "main", "CruiseControlClientError", "Responder"]
