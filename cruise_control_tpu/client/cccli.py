"""``cccli`` — the console client.

Reference parity: cruise-control-client client/cccli.py:230 + Endpoint.py:637
— an argparse subcommand per REST endpoint whose flags mirror that
endpoint's parameter schema (the schemas are shared with the server, so
client and server can never drift, unlike the reference's hand-mirrored
parameter lists).
"""

from __future__ import annotations

import argparse
import json
import sys

from ..api.endpoints import EndPoint
from ..api.parameters import SCHEMAS, _COMMON, _bool
from .responder import CruiseControlClientError, Responder


def _add_endpoint_parser(sub: argparse._SubParsersAction,
                         endpoint: EndPoint) -> None:
    p = sub.add_parser(endpoint.name.lower(),
                       help=f"{endpoint.method} {endpoint.path}")
    for name, coerce in {**_COMMON, **SCHEMAS[endpoint]}.items():
        if coerce is _bool:
            # tri-state: absent → server default, --x true/false → explicit
            p.add_argument(f"--{name}", choices=["true", "false"], default=None)
        else:
            p.add_argument(f"--{name}", default=None)
    p.set_defaults(endpoint=endpoint)


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="cccli", description="cruise-control-tpu console client")
    parser.add_argument("-a", "--address", default="http://localhost:9090",
                        help="server base address")
    parser.add_argument("--prefix", default="kafkacruisecontrol",
                        help="API url prefix")
    parser.add_argument("--poll-interval", type=float, default=1.0)
    parser.add_argument("--timeout", type=float, default=600.0)
    parser.add_argument("--header", action="append", default=[],
                        metavar="NAME:VALUE", help="extra request header")
    sub = parser.add_subparsers(dest="command", required=True)
    for endpoint in EndPoint:
        _add_endpoint_parser(sub, endpoint)
    return parser


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    endpoint: EndPoint = args.endpoint
    skip = {"address", "prefix", "poll_interval", "timeout", "header",
            "command", "endpoint"}
    params = {k: v for k, v in vars(args).items()
              if k not in skip and v is not None}
    headers = {}
    for h in args.header:
        name, _, value = h.partition(":")
        headers[name.strip()] = value.strip()
    responder = Responder(f"{args.address.rstrip('/')}/{args.prefix}",
                          headers=headers, poll_interval_s=args.poll_interval,
                          timeout_s=args.timeout)
    try:
        body = responder.retrieve_response(endpoint.method, endpoint.path,
                                           params)
    except CruiseControlClientError as e:
        print(json.dumps(e.body if isinstance(e.body, dict)
                         else {"error": str(e.body)}, indent=2),
              file=sys.stderr)
        return 1
    print(json.dumps(body, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
