"""HTTP responder with async-task polling.

Reference parity: cruise-control-client Responder.py:144 — issue the
request, and when the server answers with an in-progress body, re-issue it
with the returned ``User-Task-ID`` header until the operation completes.
stdlib urllib only (the reference uses `requests`).
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Mapping

USER_TASK_HEADER = "User-Task-ID"


class CruiseControlClientError(Exception):
    def __init__(self, status: int, body: dict | str):
        super().__init__(f"HTTP {status}: {body}")
        self.status = status
        self.body = body


class Responder:
    def __init__(self, base_url: str, headers: Mapping[str, str] | None = None,
                 poll_interval_s: float = 1.0, timeout_s: float = 600.0):
        self._base = base_url.rstrip("/")
        self._headers = dict(headers or {})
        self._poll_interval_s = poll_interval_s
        self._timeout_s = timeout_s

    def _request(self, method: str, endpoint: str, params: Mapping[str, Any],
                 extra_headers: Mapping[str, str]) -> tuple[int, dict, dict]:
        query = urllib.parse.urlencode(
            {k: str(v).lower() if isinstance(v, bool) else v
             for k, v in params.items() if v is not None})
        url = f"{self._base}/{endpoint.lower()}"
        if query:
            url += f"?{query}"
        req = urllib.request.Request(url, method=method,
                                     headers={**self._headers, **extra_headers})
        try:
            with urllib.request.urlopen(req, timeout=120) as resp:
                return (resp.status, json.loads(resp.read() or b"{}"),
                        dict(resp.headers))
        except urllib.error.HTTPError as e:
            try:
                body = json.loads(e.read())
            except Exception:
                body = {"errorMessage": str(e)}
            raise CruiseControlClientError(e.code, body)

    def retrieve_response(self, method: str, endpoint: str,
                          params: Mapping[str, Any] | None = None) -> dict:
        """Issue + poll to completion (Responder's retrieve_response loop)."""
        params = params or {}
        deadline = time.time() + self._timeout_s
        task_headers: dict[str, str] = {}
        while True:
            status, body, headers = self._request(method, endpoint, params,
                                                  task_headers)
            if "progress" not in body:
                return body
            task_id = headers.get(USER_TASK_HEADER)
            if task_id:
                task_headers[USER_TASK_HEADER] = task_id
            if time.time() > deadline:
                raise CruiseControlClientError(
                    408, {"errorMessage": "operation did not finish in time",
                          "userTaskId": task_id})
            time.sleep(self._poll_interval_s)
