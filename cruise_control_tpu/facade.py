"""The orchestration facade: the single object wiring monitor, analyzer,
executor, and anomaly detection.

Reference parity: KafkaCruiseControl.java:78 (constructor wiring :112-129,
startUp:221, proposal/execute delegation) plus the operation runnables
(servlet/handler/async/runnable/: RebalanceRunnable:115,
AddBrokersRunnable, RemoveBrokersRunnable, DemoteBrokerRunnable,
FixOfflineReplicasRunnable, UpdateTopicConfigurationRunnable,
ProposalsRunnable) — here each runnable body is a facade method; the async
wrapper lives in api/user_tasks.py.

Broker-scoped operations are expressed as state edits on the tensor model
(set_broker_state — NEW for additions, DEAD for removals, DEMOTED for
demotions) followed by the same batched goal chain; the reference does the
identical thing on its object graph before optimizing.
"""

from __future__ import annotations

import contextvars
import dataclasses
import logging
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Callable, Mapping, Sequence

import numpy as np

from .analyzer.constraint import OptimizationOptions
from .analyzer.optimizer import (
    GoalOptimizer, OptimizerResult, goals_by_priority,
)
from .analyzer.proposals import ExecutionProposal
from .common.broker_state import BrokerState
from .config.cruise_control_config import CruiseControlConfig
from .detector.broker_failure import BrokerFailureDetector
from .detector.disk_failure import DiskFailureDetector
from .detector.goal_violation import GoalViolationDetector
from .detector.maintenance import (
    InMemoryMaintenanceEventReader, MaintenanceEventDetector,
)
from .detector.manager import AnomalyDetectorManager
from .detector.metric_anomaly import MetricAnomalyDetector
from .detector.notifier import AnomalyNotifier, SelfHealingNotifier
from .detector.topic_anomaly import TopicAnomalyDetector
from .executor.admin import AdminBackend
from .executor.concurrency import ConcurrencyAdjusterConfig, ConcurrencyCaps
from .executor.executor import Executor
from .model.tensors import ClusterMeta, ClusterTensors, set_broker_state
from .monitor.load_monitor import (
    LoadMonitor, ModelCompletenessRequirements, NotEnoughValidWindowsError,
)
from .monitor.task_runner import SamplingMode

LOG = logging.getLogger(__name__)
OPERATION_LOG = logging.getLogger("cruise_control_tpu.operation")

# Per-request execution overrides (strategy, concurrency dict, extras dict)
# — thread/task scoped via ContextVar; see CruiseControl.execution_overrides.
# extras keys: progress_check_interval_s, replication_throttle,
# throttle_excluded_brokers, stop_ongoing_execution.
_EXECUTION_OVERRIDES: contextvars.ContextVar[tuple] = \
    contextvars.ContextVar("execution_overrides", default=(None, {}, {}))


def _traced_op(name: str):
    """Root-span wrapper for the operation runnables: each facade
    operation becomes one trace (operation attribute = runnable name;
    cluster attribution comes from the ambient sensor label). Child
    spans — aggregate, model assembly, per-goal solve, execution — open
    contextvar-deep with no plumbing."""
    import functools

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(self, *args, **kwargs):
            from .utils.tracing import TRACER
            with TRACER.span(name, operation=name):
                return fn(self, *args, **kwargs)
        return wrapper
    return deco


@dataclass
class OperationResult:
    """What every operation returns (the runnable's computeResult)."""

    operation: str
    dryrun: bool
    optimizer_result: OptimizerResult | None = None
    proposals: tuple[ExecutionProposal, ...] = ()
    executed: bool = False
    reason: str = ""
    extra: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        d = {"operation": self.operation, "dryrun": self.dryrun,
             "executed": self.executed, "reason": self.reason,
             "numProposals": len(self.proposals)}
        if self.optimizer_result is not None:
            d["summary"] = self.optimizer_result.summary()
        d.update(self.extra)
        return d


class CruiseControl:
    """The KafkaCruiseControl facade for the TPU framework."""

    def __init__(self, config: CruiseControlConfig, admin: AdminBackend,
                 load_monitor: LoadMonitor | None = None,
                 executor: Executor | None = None,
                 notifier: AnomalyNotifier | None = None,
                 optimizer: GoalOptimizer | None = None,
                 clock: "Callable[[], float] | None" = None,
                 configure_observability: bool = True):
        self._config = config
        # Injectable clock (round 11): when given, simulated time drives
        # every detector-pipeline time comparison — anomaly tick
        # scheduling, broker-failure escalation thresholds, maintenance
        # idempotence windows, and the model breaker's recovery window —
        # so the digital-twin simulator replays hours of cluster drift
        # wall-clock-free. None (production) keeps wall time everywhere.
        self._clock = clock
        self._now_ms = (lambda: int(clock() * 1000)) \
            if clock is not None else None
        # Chaos harness (round 9): ``chaos.enabled=true`` wraps the admin
        # backend in the deterministic fault injector — game-day drills
        # run the REAL pipeline against injected timeouts/transients/
        # partial metadata, exercising the same resilience layer the
        # chaos suite pins.
        if config.get_boolean("chaos.enabled"):
            from .testing.chaos import ChaosAdminBackend
            if not isinstance(admin, ChaosAdminBackend):
                # Idempotent: a builder that already wrapped (so its
                # monitor/sampler share the SAME fault schedule — see
                # api/app.build_live_cruise_control) is left alone.
                admin = ChaosAdminBackend.from_config(admin, config)
        self._admin = admin
        # Resilience (round 9): one retry policy + one breaker per
        # facade, shared by the executor's admin calls and the proposal
        # path's stale-cache fallback below.
        from .utils.resilience import CircuitBreaker, RetryPolicy
        self._retry_policy = RetryPolicy.from_config(config)
        self._model_breaker = CircuitBreaker.from_config(
            config, name="model",
            clock=clock if clock is not None else time.monotonic)
        # Observability wiring (round 8): one process-wide tracer,
        # (re)configured from each facade's config — fleet overlays
        # inherit the tracing.* keys from the base config, and per-cluster
        # attribution comes from the ambient cluster label, not from
        # per-facade tracers. XLA telemetry hooks jax.monitoring once.
        # ``configure_observability=False`` (digital-twin simulators,
        # other EMBEDDED facades) leaves the process-wide tracer/telemetry
        # exactly as the HOST configured them: a ?what_if= replay must not
        # rewrite the serving process's tracing settings, and bench
        # --scenarios must keep its own JSONL dump path.
        if configure_observability:
            from .utils import xla_telemetry
            from .utils.flight_recorder import FLIGHT
            from .utils.tracing import TRACER
            TRACER.configure(
                enabled=config.get_boolean("tracing.enabled"),
                max_traces=config.get_int("tracing.max.traces"),
                jsonl_path=config.get("tracing.jsonl.path") or None,
                jsonl_max_bytes=config.get_long("tracing.jsonl.max.bytes"),
                jsonl_max_files=config.get_int("tracing.jsonl.max.files"))
            FLIGHT.configure(
                enabled=config.get_boolean("solver.flight.recorder.enabled"),
                max_passes=config.get_int("solver.flight.recorder.max.passes"),
                ring_rounds=config.get_int(
                    "solver.flight.recorder.ring.rounds"))
            xla_telemetry.install(
                enabled=config.get_boolean("xla.telemetry.enabled"))
        self._load_monitor = load_monitor or LoadMonitor(config, admin)
        self._executor = executor or Executor(
            admin,
            caps=ConcurrencyCaps(
                inter_broker_per_broker=config.get_int(
                    "num.concurrent.partition.movements.per.broker"),
                cluster_inter_broker=config.get_int(
                    "max.num.cluster.partition.movements"),
                intra_broker_per_broker=config.get_int(
                    "num.concurrent.intra.broker.partition.movements"),
                leadership_cluster=config.get_int(
                    "num.concurrent.leader.movements"),
            ),
            replication_throttle=config.get("default.replication.throttle"),
            on_sampling_mode_change=self._on_execution_sampling_change,
            adjuster_enabled=config.get_boolean("concurrency.adjuster.enabled"),
            adjuster_interval_s=config.get_long(
                "concurrency.adjuster.interval.ms") / 1000.0,
            adjuster_config=ConcurrencyAdjusterConfig.from_config(config),
            broker_metrics_supplier=lambda: (
                self._load_monitor.latest_broker_metrics(
                    [n for n, _f in ConcurrencyAdjusterConfig.LIMIT_METRICS])),
            inter_rate_alert_mb_s=config.get_double(
                "inter.broker.replica.movement.rate.alerting.threshold"),
            intra_rate_alert_mb_s=config.get_double(
                "intra.broker.replica.movement.rate.alerting.threshold"),
            retry_policy=self._retry_policy,
            dead_letter_attempts=config.get_int(
                "resilience.executor.dead.letter.attempts"))
        # ``optimizer`` injection is the fleet's solver-sharing seam
        # (fleet.registry): every cluster facade in a federated process
        # runs the SAME GoalOptimizer (and device/mesh), so bucketed
        # shapes land in one compiled-kernel set.
        self._optimizer = optimizer or GoalOptimizer(config)
        self._notifier = notifier or SelfHealingNotifier(
            config, now_ms=self._now_ms)
        # Heal ledger (round 16): the anomaly-lifecycle journal. One
        # PER FACADE — a fleet's clusters and an embedded digital twin
        # each journal on their own (possibly simulated) clock, the same
        # isolation discipline as configure_observability. Served as
        # GET /heals; the detector manager opens chains at detection and
        # the facade/scheduler/executor phases attach ambiently.
        from .utils.heal_ledger import HealLedger
        self.heal_ledger = HealLedger(
            enabled=config.get_boolean("heal.ledger.enabled"),
            max_chains=config.get_int("heal.ledger.max.chains"),
            max_phases=config.get_int("heal.ledger.max.phases"),
            clock=clock if clock is not None else time.time)
        # Request journeys + SLO engine (round 21): per-facade like the
        # heal ledger — a fleet's clusters and an embedded twin each
        # keep their own ring and their own objective windows, on their
        # own (possibly simulated) clock.
        from .serving.journey import JourneyLog
        self.journeys = JourneyLog(
            enabled=config.get_boolean("journey.enabled"),
            max_entries=config.get_int("journey.max.entries"),
            monotonic=clock if clock is not None else time.monotonic,
            clock=clock if clock is not None else time.time)
        from .utils.slo import SloRegistry
        self.slo = SloRegistry.from_config(
            config, clock=clock if clock is not None else time.time)
        self._anomaly_detector = AnomalyDetectorManager(
            config, self._notifier, facade=self, clock=self._clock,
            ledger=self.heal_ledger)
        self.maintenance_reader = self._configured_maintenance_reader(config)
        # Executor.java demotion/removal history consumed by the
        # exclude_recently_* request parameters and the ADMIN drop_* params;
        # initialized BEFORE detector wiring, which shares the live
        # history. Entries are TIMESTAMPED and expire after
        # *.history.retention.time.ms on the injected clock (reference
        # parity: Executor.java removalHistory/demotionHistory retention).
        # The digital-twin multi_az_failure scenario surfaced why a bare
        # set is wrong: a self-healed broker removal excluded the broker
        # from replica moves FOREVER, so after the failed AZ revived,
        # goal-violation detection reported "unfixable
        # ReplicaDistributionGoal" endlessly instead of rebalancing onto
        # the recovered brokers.
        self._removal_history: dict[int, int] = {}   # broker -> stamp ms
        self._demotion_history: dict[int, int] = {}
        self._removal_retention_ms = config.get_long(
            "removal.history.retention.time.ms")
        self._demotion_retention_ms = config.get_long(
            "demotion.history.retention.time.ms")
        # Guards ALL reads/writes of the two histories above (API threads
        # mutate them; the detection thread snapshots them). Taken INSIDE
        # the recently_*_brokers properties — callers must not hold it.
        self.excluded_sets_lock = threading.Lock()
        from .analyzer.plugins import (
            compile_excluded_topics_pattern, options_generator_from_config,
        )
        self._options_generator = options_generator_from_config(config)
        # Fallback for CUSTOM generators that lack merged_excluded_topics:
        # the config's never-move contract must hold regardless of which
        # generator is plugged in.
        self._excluded_topics_rx = compile_excluded_topics_pattern(config)
        # Predictive rebalancing (round 19): one forecast engine per
        # facade (the heal-ledger isolation discipline — a fleet's
        # clusters and an embedded twin each forecast their OWN
        # monitor's history). Off-means-off: with forecast.enabled=false
        # the engine and its detector cost one config read per tick and
        # serving behavior is byte-identical.
        from .forecast import ForecastEngine
        self.forecast_engine = ForecastEngine(config, self._load_monitor)
        # Pacer promotion flag: a predicted violation's precompute marks
        # this cluster due for an immediate paced cache fill regardless
        # of its cadence (fleet/scheduler.pace_once consumes + clears).
        self.predicted_precompute_pending = False
        self._wire_detectors()

        self._proposal_cache: tuple[int, float, OptimizerResult] | None = None
        self._proposal_lock = threading.Lock()
        # Serializes the EXPENSIVE proposal computation (the reference's
        # in-progress coordination, GoalOptimizer.java:152-203): the
        # precompute loop and an API request must not run two identical
        # optimization passes concurrently.
        self._proposal_compute_lock = threading.Lock()
        self._stop_precompute: threading.Event | None = None
        self._precompute_thread: threading.Thread | None = None
        self._started = False
        # Fleet seam (ROADMAP item 3c tail, round 15): when the registry
        # wires a nonzero width, goal-chain solves — self-healing fixes
        # and on-demand operations included — run through the BATCHED
        # megabatch kernels at occupancy 1 instead of compiling the solo
        # chain programs: one compiled program per bucket shape serves
        # precompute fills, fixes, and futures alike, and per-request
        # exclusion options ride the batched mask assembler.
        self.megabatch_solve_width = 0
        # Always-hot solver (round 18): the last ACCEPTED (assignment,
        # leader_slot) seeds the next default-chain solve — under
        # sustained drift most goals are already satisfied at the
        # previous target, so rounds-to-convergence collapses. The
        # quality fallback (_warm_quality_ok) re-solves cold whenever a
        # warm result falls below the sentry band, so warm starts can
        # never silently degrade proposals. One store per facade = one
        # per cluster (the heal-ledger isolation discipline).
        from .warmstart import WarmSeedStore
        self._warm_enabled = config.get_boolean("solver.warm.start.enabled")
        self._warm_band = config.get_double("solver.warm.start.quality.band")
        # Warm-band pre-check (round 19, ROADMAP 3a tail): score the
        # seed against the CURRENT loads in one batched stats program
        # before committing to the full warm chain — a seed that
        # drifted band-worse is skipped without paying attempt+fallback.
        self._warm_precheck = config.get_boolean(
            "solver.warm.start.precheck.enabled")
        self._warm_seeds = WarmSeedStore()
        # Pending warm context across the precompute seams (set by
        # precompute_inputs, consumed by store_precomputed on the SAME
        # worker thread — the megabatch runner's prepare/complete both
        # run inside one scheduler turn).
        self._tls_warm = threading.local()
        from .detector.provisioner import BasicProvisioner
        self.provisioner = BasicProvisioner()

    # -- wiring ------------------------------------------------------------
    @staticmethod
    def _configured_maintenance_reader(config: CruiseControlConfig):
        """maintenance.event.reader.class plugin resolution
        (AnomalyDetectorConfig.MAINTENANCE_EVENT_READER_CLASS_CONFIG). The
        default in-memory reader takes no arguments; custom readers are
        instantiated bare and may read their own config via attributes."""
        from .config.abstract_config import resolve_class
        from .detector.maintenance_serde import TopicMaintenanceEventReader
        spec = config.get("maintenance.event.reader.class")
        cls = resolve_class(spec) if isinstance(spec, str) else spec
        if cls is InMemoryMaintenanceEventReader or cls is None:
            return InMemoryMaintenanceEventReader()
        if cls is TopicMaintenanceEventReader:
            # Live Kafka binding (MaintenanceEventTopicReader.java:350):
            # consume plans an ops pipeline produces to
            # ``maintenance.event.topic`` over the wire client.
            bootstrap = config.get("bootstrap.servers")
            if not bootstrap:
                LOG.warning("maintenance.event.reader.class is the topic "
                            "reader but bootstrap.servers is unset; using "
                            "the in-memory reader")
                return InMemoryMaintenanceEventReader()
            from .kafka.transport import KafkaMetricsTransport
            transport = KafkaMetricsTransport(
                bootstrap, topic=config.get("maintenance.event.topic"),
                num_partitions=1)
            return TopicMaintenanceEventReader(transport)
        try:
            return cls()
        except TypeError:
            # Reader needs deployment wiring (e.g. a Kafka transport):
            # leave construction to the embedder, fall back in-memory.
            LOG.warning("maintenance reader %s needs explicit construction; "
                        "using the in-memory reader", spec)
            return InMemoryMaintenanceEventReader()

    def _wire_detectors(self) -> None:
        cfg, report = self._config, self._anomaly_detector.report
        interval = cfg.get_long("anomaly.detection.interval.ms")
        mgr = self._anomaly_detector
        self.goal_violation_detector = GoalViolationDetector(
            cfg, self._load_monitor, self._optimizer, report)

        # Detection excludes the same recently-removed/demoted brokers the
        # user-facing operations do — the history properties snapshot
        # under the facade's lock, so the detection thread never iterates
        # a dict an API thread is mutating.
        def _excluded_snapshot():
            return (tuple(sorted(self.recently_demoted_brokers)),
                    tuple(sorted(self.recently_removed_brokers)))

        self.goal_violation_detector.excluded_brokers_supplier = \
            _excluded_snapshot
        mgr.add_detector(self.goal_violation_detector, interval)
        # Predictive twin of the goal-violation detector (round 19):
        # scores the forecaster's projected model through the same
        # batched goal-stats program and reports predicted violations as
        # first-class anomalies. Registered unconditionally — a disabled
        # engine makes its tick a single config read (the noop-overhead
        # guard family).
        from .detector.predictive import PredictiveViolationDetector
        self.predictive_detector = PredictiveViolationDetector(
            cfg, self.forecast_engine, self._optimizer, report,
            ledger=self.heal_ledger,
            clock=self._clock if self._clock is not None else time.time)
        self.predictive_detector.excluded_brokers_supplier = \
            _excluded_snapshot
        mgr.add_detector(self.predictive_detector, interval)
        # SLO burn detector (round 21): evaluates the facade's objective
        # registry's multi-window burn rule and raises SLO_BURN anomalies
        # through the same manager/ledger path. Registered
        # unconditionally — a disabled registry makes its tick one
        # attribute read (the noop-overhead guard family).
        from .detector.slo_burn import SloBurnDetector
        self.slo_burn_detector = SloBurnDetector(
            self.slo, report, ledger=self.heal_ledger)
        mgr.add_detector(self.slo_burn_detector, interval)
        mgr.add_detector(BrokerFailureDetector(
            self._admin, report,
            failed_brokers_file_path=cfg.get("failed.brokers.file.path"),
            now_ms=self._now_ms),
            interval)
        mgr.add_detector(DiskFailureDetector(self._admin, report), interval)
        mgr.add_detector(MetricAnomalyDetector(
            self._load_monitor.broker_aggregator, report, config=cfg),
            cfg.get("metric.anomaly.detection.interval.ms") or interval)
        target_rf = cfg.get("self.healing.target.topic.replication.factor")
        if target_rf:
            mgr.add_detector(TopicAnomalyDetector(
                self._admin, report, cfg, desired_rf=int(target_rf),
                topic_pattern=cfg.get("topic.anomaly.topic.pattern")), interval)
        idem_retention = cfg.get_long("maintenance.event.idempotence."
                                      "retention.ms")
        if not cfg.get_boolean("maintenance.event.enable.idempotence"):
            idem_retention = 0  # zero-retention cache never matches
        mgr.add_detector(MaintenanceEventDetector(
            self.maintenance_reader, report,
            idempotence_retention_ms=idem_retention,
            now_ms=self._now_ms), interval)

    def _on_execution_sampling_change(self, executing: bool) -> None:
        """Executor.java:1408-1424 — reduce sampling scope during moves and
        RESTORE the prior mode afterwards (a user-initiated pause must
        survive an execution that completes meanwhile)."""
        runner = self._load_monitor.task_runner
        try:
            if executing:
                self._sampling_mode_before_execution = runner.sampling_mode
                runner.set_mode(SamplingMode.ONGOING_EXECUTION,
                                reason="proposal execution")
            elif runner.sampling_mode is SamplingMode.ONGOING_EXECUTION:
                restore = getattr(self, "_sampling_mode_before_execution",
                                  SamplingMode.RUNNING)
                if restore is SamplingMode.ONGOING_EXECUTION:
                    restore = SamplingMode.RUNNING
                runner.set_mode(restore, reason="execution finished")
        except Exception:
            LOG.exception("could not flip sampling mode")

    # -- lifecycle (KafkaCruiseControl.startUp:221) ------------------------
    def start_up(self, block_on_load: bool = True,
                 start_precompute: bool = True) -> None:
        """``start_precompute=False`` leaves the facade's own proposal
        precompute loop off — fleet deployments route precompute through
        the FleetScheduler's pacer instead (one device, many clusters:
        per-facade loops would contend for it unscheduled)."""
        # Always-hot solver (round 18): point XLA's persistent compile
        # cache at the configured dir (serving processes get it without
        # wrapper scripts — idempotent, safest before the first solve
        # jit), then prewarm the known bucket-shape set in a background
        # thread so a fresh replica serves its first rebalance in
        # seconds. Both no-op when their config switches are off; the
        # prewarm manager is per-optimizer, so fleet clusters sharing
        # one solver prewarm exactly once.
        from .warmstart import configure_compile_cache, ensure_prewarm
        configure_compile_cache(self._config)
        ensure_prewarm(self._optimizer, self._config)
        self._load_monitor.start_up(block_on_load=block_on_load)
        self._anomaly_detector.start_detection()
        self._started = True
        if start_precompute and (self._precompute_thread is None
                                 or not self._precompute_thread.is_alive()):
            self._stop_precompute = threading.Event()
            self._precompute_thread = threading.Thread(
                target=self._proposal_precompute_loop, daemon=True,
                name="proposal-precompute")
            self._precompute_thread.start()

    def _proposal_precompute_loop(self) -> None:
        """GoalOptimizer.run (GoalOptimizer.java:152-203): keep the cached
        proposals fresh in the background so a PROPOSALS/REBALANCE request
        hits a warm cache. Refresh-ahead: an entry with less than one
        wake interval of budget left is recomputed NOW, so requests never
        find the cache expired between wakes. Tolerates a not-ready load
        model."""
        expiration_s = self._config.get_long("proposal.expiration.ms") / 1000.0
        interval_s = max(1.0, expiration_s / 2.0)
        # Refresh-ahead headroom: 1.5 wake intervals so an entry never
        # expires between one wake deciding "fresh" and the next wake's
        # recompute finishing — clamped for pathologically short budgets
        # (expiration < interval), where some inline computes are what the
        # operator's config demands.
        margin_s = min(1.5 * interval_s, 0.75 * expiration_s)
        stop = self._stop_precompute
        while not stop.wait(interval_s):
            try:
                gen = self._load_monitor.model_generation
                if self._cached_proposals_fresh(gen, margin_s=margin_s):
                    continue
                self.proposals(_freshness_margin_s=margin_s)
                from .utils.sensors import SENSORS
                SENSORS.count("analyzer_proposal_precompute_runs")
            except Exception:  # noqa: BLE001 — model may not be ready yet
                LOG.debug("proposal precompute skipped", exc_info=True)

    def shutdown(self) -> None:
        if self._stop_precompute is not None:
            self._stop_precompute.set()
        if self._precompute_thread is not None \
                and self._precompute_thread.is_alive():
            # Join BEFORE tearing down the monitor/executor: an in-flight
            # precompute must not race a half-shut-down load monitor.
            self._precompute_thread.join(timeout=30.0)
        # Forget the thread either way — a later start_up() must spawn a
        # fresh loop even if this join timed out (the old thread exits on
        # its own already-set stop event).
        self._precompute_thread = None
        self._anomaly_detector.shutdown()
        self._executor.stop_execution()
        self._load_monitor.shutdown()
        self._started = False

    # -- collaborators -----------------------------------------------------
    @property
    def config(self) -> CruiseControlConfig:
        return self._config

    @property
    def load_monitor(self) -> LoadMonitor:
        return self._load_monitor

    @property
    def executor(self) -> Executor:
        return self._executor

    @property
    def optimizer(self) -> GoalOptimizer:
        return self._optimizer

    @property
    def anomaly_detector(self) -> AnomalyDetectorManager:
        return self._anomaly_detector

    # -- model helpers -----------------------------------------------------
    def _model(self, requirements: ModelCompletenessRequirements | None = None,
               allow_capacity_estimation: bool = True,
               ) -> tuple[ClusterTensors, ClusterMeta]:
        return self._load_monitor.cluster_model(
            requirements, allow_capacity_estimation=allow_capacity_estimation)

    def _chain_and_model(self, goals, use_ready_default_goals: bool,
                         data_from: str | None,
                         allow_capacity_estimation: bool):
        """Shared preamble of every goal-based operation: resolve the goal
        chain (ready-filtered when asked), then build the model under the
        chain's data_from-derived completeness requirements."""
        chain = self._goal_chain(goals, use_ready_default_goals)
        state, meta = self._model(
            self._requirements_for(data_from, chain),
            allow_capacity_estimation=allow_capacity_estimation)
        # Heal ledger: a fix operation's model build is a phase on its
        # correlation chain (NO_HEAL no-op outside a heal scope).
        from .utils.heal_ledger import current_heal
        current_heal().phase("model_built",
                             brokers=len(meta.broker_ids),
                             partitions=len(meta.partition_index))
        return chain, state, meta

    def _requirements_for(self, data_from: str | None, chain,
                          ) -> ModelCompletenessRequirements | None:
        """data_from request param → model completeness requirements
        (GoalBasedOptimizationParameters.getRequirements:93 merged weaker
        with the chain's own requirements): valid_windows weakens the
        window count to 1; valid_partitions keeps the chain's window
        requirement but drops the partition-coverage floor."""
        if not data_from:
            return None
        df = data_from.lower()
        nw = self._config.get_int("num.partition.metrics.windows")
        ratio = self._config.get_double("min.valid.partition.ratio")
        if df == "valid_windows":
            return ModelCompletenessRequirements(1, ratio)
        if df == "valid_partitions":
            goal_windows = max(
                (g.completeness_requirements(nw, ratio)[0] for g in chain),
                default=1)
            return ModelCompletenessRequirements(goal_windows, 0.0)
        raise ValueError(f"unknown data_from {data_from!r} "
                         "(valid_windows | valid_partitions)")

    def _admin_call(self, op: str, fn):
        """Admin-backend read under the facade's retry policy (bare when
        resilience is disabled)."""
        from .utils.resilience import call_with_resilience
        return call_with_resilience(op, fn, policy=self._retry_policy)

    def alive_brokers(self) -> set[int]:
        """Live broker set (anomaly re-validation + dashboards)."""
        return self._admin_call("admin.alive_brokers",
                                self._admin.alive_brokers)

    def ready_for_self_healing(self) -> bool:
        """Completeness gate consulted before anomaly fixes
        (AnomalyDetectorManager.java:513)."""
        try:
            state = self._load_monitor.state()
        except Exception:
            return False
        return state.num_valid_windows >= 1

    def _broker_indices(self, meta: ClusterMeta, broker_ids: Sequence[int],
                        ) -> list[int]:
        idx = {bid: i for i, bid in enumerate(meta.broker_ids)}
        missing = [b for b in broker_ids if b not in idx]
        if missing:
            raise ValueError(f"brokers not in cluster model: {missing}")
        return [idx[b] for b in broker_ids]

    def _mark_brokers(self, state: ClusterTensors, meta: ClusterMeta,
                      broker_ids: Sequence[int], code: BrokerState,
                      ) -> ClusterTensors:
        for i in self._broker_indices(meta, broker_ids):
            state = set_broker_state(state, np.int32(i), int(code))
        return state

    def _goal_chain(self, goals: Sequence[str] | None,
                    use_ready_default_goals: bool = False):
        names = list(goals) if goals else None
        chain = goals_by_priority(self._config, names)
        if names is None and use_ready_default_goals:
            ready = self.ready_goals(chain)
            if not ready:
                raise ValueError(
                    "use_ready_default_goals: no default goal's model-"
                    "completeness requirement is currently met")
            chain = ready
        return chain

    def ready_goals(self, chain=None, monitor_state=None) -> list:
        """The subset of ``chain`` (default: the configured goal chain)
        whose model-completeness requirements the monitor currently meets
        (Goal.clusterModelCompletenessRequirements × the monitor's valid
        windows/coverage; the ``use_ready_default_goals`` request param and
        the STATE AnalyzerState.readyGoals field). Pass ``monitor_state``
        when one is already computed — LoadMonitor.state() walks the whole
        partition metadata, too expensive to repeat per request."""
        if chain is None:
            chain = goals_by_priority(self._config)
        try:
            ms = monitor_state or self._load_monitor.state()
            windows, coverage = ms.num_valid_windows, \
                ms.monitored_partitions_percentage
        except Exception:  # noqa: BLE001 — monitor not started yet
            return []
        num_windows = self._config.get_int("num.partition.metrics.windows")
        min_ratio = self._config.get_double("min.valid.partition.ratio")
        out = []
        for g in chain:
            need_w, need_ratio = g.completeness_requirements(
                num_windows, min_ratio)
            if windows >= need_w and coverage >= need_ratio:
                out.append(g)
        return out

    @contextmanager
    def execution_overrides(self,
                            replica_movement_strategies: Sequence[str] = (),
                            concurrency: Mapping[str, int] | None = None,
                            extras: Mapping[str, Any] | None = None):
        """Per-request execution overrides (ParameterUtils), scoped to the
        operation run inside the ``with`` block. Carried in a ContextVar:
        each request thread (ThreadingHTTPServer / user-task pool) sees only
        ITS overrides — concurrent requests cannot clobber or clear each
        other's — and exit always restores, so a dry run, zero-proposal
        result, or optimizer exception never leaks them.

        ``extras``: progress_check_interval_s (float),
        replication_throttle (int rate override),
        throttle_excluded_brokers (broker ids to leave unthrottled),
        stop_ongoing_execution (bool: gracefully stop + wait before this
        execution, RunnableUtils.maybeStopOngoingExecutionToModifyAndWait)."""
        strategy = None
        if replica_movement_strategies:
            from .executor.strategy import strategy_chain
            strategy = strategy_chain(list(replica_movement_strategies))
        token = _EXECUTION_OVERRIDES.set(
            (strategy, dict(concurrency or {}), dict(extras or {})))
        try:
            yield
        finally:
            _EXECUTION_OVERRIDES.reset(token)

    def _maybe_execute(self, result: OptimizerResult, dryrun: bool,
                       operation: str, reason: str, uuid: str = "") -> bool:
        if dryrun or not result.proposals:
            return False
        OPERATION_LOG.info("%s executing %d proposals (reason: %s)",
                           operation, len(result.proposals), reason)
        strategy, concurrency, extras = _EXECUTION_OVERRIDES.get()
        if extras.get("stop_ongoing_execution") \
                and self._executor.has_ongoing_execution():
            # maybeStopOngoingExecutionToModifyAndWait (RunnableUtils.java):
            # gracefully stop the current execution, wait for it to wind
            # down, then start this one.
            OPERATION_LOG.info("%s stopping ongoing execution first", operation)
            self._executor.stop_execution()
            deadline = time.time() + 60.0
            while self._executor.has_ongoing_execution() \
                    and time.time() < deadline:
                time.sleep(0.05)
        self._executor.execute_proposals(
            result.proposals, uuid=uuid, strategy=strategy,
            concurrency_overrides=concurrency or None,
            progress_check_interval_s=extras.get("progress_check_interval_s"),
            replication_throttle=extras.get("replication_throttle"),
            throttle_excluded_brokers=extras.get(
                "throttle_excluded_brokers", ()))
        return True

    def _config_excluded_topics(self, topic_names,
                                explicit=()) -> tuple[str, ...]:
        """Explicit exclusions ∪ config-regex matches. Delegates to the
        generator's single merge implementation; a custom generator
        without the helper falls back to the facade's own compiled
        pattern — the config's never-move contract must hold regardless
        of which generator is plugged in."""
        merge = getattr(self._options_generator, "merged_excluded_topics",
                        None)
        if merge is not None:
            return merge(topic_names, explicit)
        merged = set(explicit)
        if self._excluded_topics_rx is not None:
            merged.update(t for t in topic_names
                          if self._excluded_topics_rx.fullmatch(t))
        return tuple(sorted(merged))

    def _with_config_excluded_topics(self, meta,
                                     options: OptimizationOptions,
                                     ) -> OptimizationOptions:
        """Merge ``topics.excluded.from.partition.movement`` matches into
        the options of EVERY operation that may move partitions — the
        config contract ('never moved') must hold on the execution paths,
        not just the dryrun/detection previews."""
        merged = self._config_excluded_topics(meta.topic_names,
                                              options.excluded_topics)
        if merged == options.excluded_topics:
            return options
        import dataclasses as _dc
        return _dc.replace(options, excluded_topics=merged)

    def _movable_partition_mask(self, state, meta):
        """[P] bool (True = movable) from the merged excluded topics, or
        None when nothing is excluded — the intra-broker disk kernels'
        view of the same never-move contract."""
        excluded = set(self._config_excluded_topics(meta.topic_names))
        if not excluded:
            return None
        import jax.numpy as jnp
        bad_ids = np.asarray(
            [i for i, t in enumerate(meta.topic_names) if t in excluded])
        mask = ~np.isin(np.asarray(state.topic), bad_ids)
        return jnp.asarray(mask)

    # -- operations (the runnables) ----------------------------------------
    def _cached_proposals_fresh(self, gen: int, margin_s: float = 0.0):
        """The ONE validCachedProposal predicate
        (GoalOptimizer.validCachedProposal:232): cache entry if it matches
        the model generation and has more than ``margin_s`` of its
        expiration budget left, else None. The precompute loop passes its
        own interval as margin (refresh-ahead: the cache must never be
        found expired by a request between two wakes)."""
        expiration_s = self._config.get_long("proposal.expiration.ms") / 1000.0
        with self._proposal_lock:
            cached = self._proposal_cache
        if cached is not None and cached[0] == gen \
                and time.time() - cached[1] < expiration_s - margin_s:
            return cached
        return None

    @_traced_op("proposals")
    def proposals(self, goals: Sequence[str] | None = None,
                  ignore_proposal_cache: bool = False,
                  use_ready_default_goals: bool = False,
                  fast_mode: bool = False,
                  data_from: str | None = None,
                  allow_capacity_estimation: bool = True,
                  _freshness_margin_s: float = 0.0) -> OperationResult:
        """ProposalsRunnable — cached when the model generation and the
        expiration budget allow (GoalOptimizer.validCachedProposal:232).
        The expensive computation is serialized: a loser of the compute
        lock re-checks the cache so two callers never run the identical
        optimization concurrently (``_freshness_margin_s`` is the
        precompute loop's refresh-ahead knob)."""
        # A ready-filtered chain is a custom chain for caching purposes:
        # the cache holds full-default-chain results; a data_from override
        # is a weaker-requirement model (hasWeakerRequirement,
        # KafkaCruiseControl.ignoreProposalCache:565-583).
        # fast_mode results are quality-degraded: they must neither be
        # served from nor stored into the default-chain cache.
        use_cache = goals is None and not ignore_proposal_cache \
            and not use_ready_default_goals and data_from is None \
            and not fast_mode

        def cached_result():
            # Generation read fresh at check time: a stale pre-lock value
            # would mislabel the cache entry and defeat the dedup.
            gen = self._load_monitor.model_generation
            cached = self._cached_proposals_fresh(gen, _freshness_margin_s)
            if cached is None:
                return None
            return OperationResult(
                "proposals", dryrun=True, optimizer_result=cached[2],
                proposals=cached[2].proposals, reason="cached")

        if use_cache:
            out = cached_result()
            if out is not None:
                return out

        def compute():
            chain, state, meta = self._chain_and_model(
                goals, use_ready_default_goals, data_from,
                allow_capacity_estimation)
            options = self._options_generator.for_cached_proposal_calculation(
                meta.topic_names, ())
            if fast_mode:
                options = dataclasses.replace(options, fast_mode=True)
            # Through the shared solve seam (round 18): proposal
            # computes get warm seeding + the quality fallback, and on a
            # fleet-wired facade ride the same batched kernels as fixes
            # (occupancy-1 parity is pinned in test_fleet). Only the
            # CANONICAL default-chain compute is warm-eligible — custom
            # chains / weakened models are incomparable solve classes.
            _final, result = self._optimize(
                state, meta, chain, options,
                warm_eligible=goals is None and not use_ready_default_goals
                and data_from is None and not fast_mode)
            return result

        if goals is not None or use_ready_default_goals or fast_mode \
                or data_from is not None:
            # Custom-goal / fast-mode / weakened-model requests are never
            # cached (neither served nor STORED — a degraded result must
            # not become the canonical default-chain cache entry) and share
            # nothing with the default-chain computation — no reason to
            # serialize them behind a long-running precompute pass.
            result = compute()
        else:
            # Graceful degradation (round 9): when the model build /
            # optimization fails, serve the LAST GOOD cached proposal set
            # — any age, any generation — clearly marked stale=true,
            # instead of a hard error. Repeated failures trip the model
            # breaker (keyed by the ambient cluster label), and an OPEN
            # breaker fails fast with BreakerOpenError, which the API
            # layer renders as 503 + Retry-After.
            from .utils.sensors import current_cluster_label
            breaker = self._model_breaker
            target = current_cluster_label() or "default"
            if breaker is not None:
                breaker.guard(target)
            with self._proposal_compute_lock:
                if use_cache:
                    out = cached_result()  # a concurrent compute finished
                    if out is not None:
                        return out
                gen = self._load_monitor.model_generation
                try:
                    result = compute()
                except NotEnoughValidWindowsError:
                    # Model not ready (warmup) is not a dependency fault:
                    # feeding it to the breaker would trip 503s that
                    # outlive the warmup and mask the real diagnostic.
                    raise
                except Exception as e:
                    if breaker is not None:
                        breaker.record_failure(target)
                    with self._proposal_lock:
                        cached = self._proposal_cache
                    if cached is None or ignore_proposal_cache:
                        # No fallback to serve — or the caller EXPLICITLY
                        # refused cached answers (ignore_proposal_cache):
                        # serving stale would override their contract.
                        raise
                    LOG.warning("proposal computation failed; serving the "
                                "last good cached proposals as STALE",
                                exc_info=True)
                    # staleness_s: age of the entry being served degraded
                    # (cache stamps are wall time regardless of the sim
                    # clock — the cache itself lives on wall time). The
                    # SLO scorer and clients both read it: degraded
                    # serving is only an SLO if its DURATION is visible.
                    staleness_s = round(time.time() - cached[1], 3)
                    from .utils.sensors import SENSORS
                    SENSORS.count("proposals_stale_served")
                    SENSORS.gauge("proposals_stale_age_seconds", staleness_s)
                    # Stale-serving window correlation: any heal in
                    # flight carries the evidence that serving degraded
                    # during its window.
                    self.heal_ledger.note_stale(staleness_s)
                    # Staleness-age SLO objective: a degraded serve is
                    # one classified event (bad past the threshold).
                    self.slo.observe_staleness(staleness_s)
                    from .utils.tracing import TRACER
                    TRACER.annotate(stale=True, staleness_s=staleness_s)
                    return OperationResult(
                        "proposals", dryrun=True, optimizer_result=cached[2],
                        proposals=cached[2].proposals,
                        reason="stale cache fallback "
                               f"({type(e).__name__}: {e})",
                        extra={"stale": True, "staleness_s": staleness_s})
                if breaker is not None:
                    breaker.record_success(target)
                with self._proposal_lock:
                    self._proposal_cache = (gen, time.time(), result)
        return OperationResult("proposals", dryrun=True,
                               optimizer_result=result,
                               proposals=result.proposals)

    def _optimize(self, state, meta, chain, options: OptimizationOptions,
                  warm_eligible: bool = False,
                  ) -> tuple[Any, OptimizerResult]:
        """The single-cluster solve seam for the goal-chain operations.
        With a fleet-wired ``megabatch_solve_width`` the solve routes
        through ``optimizations_megabatch`` at occupancy 1 — the same
        compiled batched program (and the same per-cluster exclusion-mask
        assembly) the fleet's coalesced precompute fills use, so fix and
        on-demand solves pay zero extra compilations on a megabatching
        deployment. Per-cluster failures surface as the exact exception
        a serial solve would raise. Fast mode and mesh solvers keep the
        serial path (the megabatch supports neither), and so does the
        deficit-sizing regime: the batched path structurally disables
        deficit-aware count-goal sizing, and a fleet-wired deployment
        must not return different proposals than a standalone one for
        the same cluster state."""
        from .serving.journey import current_journey
        from .utils.heal_ledger import current_heal
        from .utils.sensors import SENSORS
        heal = current_heal()
        jny = current_journey()
        jny_t0 = jny.now()
        width = self.megabatch_solve_width
        batched = bool(width and not options.fast_mode
                       and self._optimizer.mesh is None
                       and not self._optimizer.deficit_sizing_active(
                           state.num_brokers))
        # Warm start (round 18): seed the search from the last accepted
        # target when one is valid for this model's index space. The
        # solve still diffs against the TRUE current ``state`` (the
        # optimizer's initial_state seam), so proposals always encode
        # moves from reality. ``warm_eligible`` scopes seeding to the
        # CANONICAL default-chain solve class (proposals/precompute):
        # broker-scoped operations, custom chains, and per-request
        # exclusion sets are incomparable solve classes — their results
        # must neither consume nor become seeds, or the single-slot
        # store's quality reference cross-contaminates (a drained
        # remove_brokers result as the gate reference would let a
        # degraded warm default solve pass; the default reference would
        # spuriously fail legitimate constrained solves).
        warm = warm_eligible and self._warm_enabled \
            and not options.fast_mode
        warm_seed = None
        warm_state = state
        if warm:
            from .warmstart import apply_seed
            warm_seed = self._warm_seeds.match(state, meta)
            if warm_seed is not None:
                warm_state = apply_seed(state, warm_seed)
            if warm_seed is not None and self._warm_precheck:
                # Warm-band pre-check (ROADMAP 3a tail): score the seed
                # against the CURRENT (drifted) loads in ONE batched
                # goal-stats program. A seed whose entry picture already
                # breaches the sentry band — a violated goal its
                # accepted solve did not have (the band rule collapses
                # to that on the 0-100 scale) — would fail the quality
                # gate after the full chain anyway; skipping here saves
                # the doomed attempt+fallback double solve. SERVED
                # results stay byte-equal: the skip path runs exactly
                # the cold solve the fallback would have (pinned in
                # tests/test_warmstart.py).
                from .warmstart import seed_band_ok
                try:
                    pre_chain, pv, _po, _poff = \
                        self._optimizer.goal_entry_stats(
                            warm_state, meta, chain, options)
                    pre_violated = {g.name for g, v in zip(pre_chain, pv)
                                    if float(v) > 1e-6}
                    pre_bal = self._optimizer.balancedness_of(
                        pre_chain, pre_violated)
                except Exception:  # noqa: BLE001 — pre-check is an
                    # optimization; a failure falls through to the
                    # gate-protected warm attempt
                    LOG.debug("warm pre-check failed; attempting warm",
                              exc_info=True)
                else:
                    if not seed_band_ok(pre_bal, pre_violated, warm_seed,
                                        self._warm_band):
                        LOG.info(
                            "warm seed band-worse on entry (balancedness "
                            "%.3f vs accepted %.3f, violated %s); "
                            "skipping the warm attempt", pre_bal,
                            warm_seed.balancedness_after,
                            sorted(pre_violated))
                        SENSORS.count("solver_warm_precheck_skips")
                        self._warm_seeds.clear()
                        warm_seed = None
                        warm_state = state
            if warm_seed is not None:
                # Counted AFTER the pre-check: a skipped seed is a cold
                # solve, and solver_warm_seeded must mean "this solve
                # actually rode a warm seed" (the warm-adoption ruler).
                SENSORS.count("solver_warm_seeded")
        # Heal-correlated solves link the flight recorder's pass ids:
        # the chain's solve_completed phase names the passSeq values that
        # resolve in GET /solver (best-effort window — a concurrent
        # solve from another thread can land inside it, so the ids are
        # filtered by this solve's ambient cluster label).
        marker = None
        if heal.recording or jny.recording:
            from .utils.flight_recorder import FLIGHT
            if FLIGHT.enabled:
                marker = FLIGHT.marker()
        if heal.recording:
            heal.phase("solve_dispatched",
                       path="megabatch" if batched else "serial",
                       warmStart=warm_seed is not None)

        def run(solve_state, initial):
            if batched:
                from .utils.sensors import current_cluster_label
                cid = current_cluster_label() or "default"
                out = self._optimizer.optimizations_megabatch(
                    [(solve_state, meta, cid, options, initial)],
                    goals=list(chain), width=width)
                r = out[0]
                if isinstance(r, Exception):
                    raise r
                return r
            return self._optimizer.optimizations(
                solve_state, meta, chain, options, initial_state=initial)

        warm_fallback = False
        if warm_seed is not None:
            try:
                res = run(warm_state, state)
            except Exception:  # noqa: BLE001 — warm failure falls back cold
                LOG.warning("warm-seeded solve failed; re-solving cold",
                            exc_info=True)
                res = None
            if res is not None and not self._warm_quality_ok(res[1],
                                                             warm_seed):
                LOG.info(
                    "warm-seeded solve below the sentry band "
                    "(balancedness %.3f vs accepted %.3f, violated %s); "
                    "re-solving cold", res[1].balancedness_after,
                    warm_seed.balancedness_after,
                    res[1].violated_goals_after)
                res = None
            if res is None:
                # The fallback contract: a warm start may cost an extra
                # solve, but can never degrade what gets served.
                warm_fallback = True
                SENSORS.count("solver_warm_fallbacks")
                self._warm_seeds.clear()
                res = run(state, None)
        else:
            res = run(state, None)
        if warm:
            self._warm_store(res[0], meta, res[1], seed=warm_seed,
                             warm_accepted=warm_seed is not None
                             and not warm_fallback)
        pass_seqs = None
        if marker is not None:
            from .utils.flight_recorder import FLIGHT
            from .utils.sensors import current_cluster_label
            # The batched path records its flight pass under the
            # same "default" fallback it solved under — the filter
            # label must match or the /solver link comes back empty
            # exactly on the megabatch path.
            label = current_cluster_label() \
                or ("default" if batched else None)
            pass_seqs = [
                p["passSeq"] for p in FLIGHT.passes_since(marker)
                if p.get("cluster") == label]
        if jny.recording:
            # The request's solve segment, linked to the same flight
            # recorder passes and (when ambient) the heal chain the
            # solve ran on account of.
            attrs: dict = {"path": "megabatch" if batched else "serial",
                           "warmStart": warm_seed is not None}
            if warm_fallback:
                attrs["warmFallback"] = True
            if pass_seqs:
                attrs["passSeqs"] = pass_seqs
            if heal.recording:
                attrs["healChainId"] = heal.chain_id
            jny.add("solve", jny.now() - jny_t0, **attrs)
        if heal.recording:
            detail: dict = {}
            if pass_seqs is not None:
                detail["passSeqs"] = pass_seqs
            if batched:
                # The fleet-wired solve rode the batched kernels at
                # occupancy 1 (one compiled program per bucket shape
                # serves fixes and precomputes alike).
                detail["batchWidth"] = width
            # Warm-path adoption attrs (round 18): GET /heals can
            # distinguish warm from cold heals, and the fingerprint
            # skip's dispatch savings are attributable per chain.
            detail["warmStart"] = warm_seed is not None
            if warm_fallback:
                detail["warmFallback"] = True
            skipped = self._optimizer.thread_dispatch_stats().get(
                "goals_skipped", 0)
            if skipped:
                detail["goalsSkipped"] = skipped
            heal.phase("solve_completed", **detail)
            heal.phase("proposal_ready", numProposals=len(res[1].proposals))
        return res

    def _warm_quality_ok(self, result, seed) -> bool:
        """The warm-start sentry band: no violated goal the seed's own
        accepted solve did not have, and balancedness within
        ``solver.warm.start.quality.band`` of the seed's (the shared
        warmstart.warm_quality_ok predicate — bench measures SERVED
        semantics with the same function)."""
        from .warmstart import warm_quality_ok
        return warm_quality_ok(result, seed.balancedness_after,
                               seed.violated_after, self._warm_band)

    def _warm_store(self, final_state, meta, result, seed=None,
                    warm_accepted: bool = False) -> None:
        """Store an accepted solve as the next seed. ``warm_accepted``
        marks a gate-passing WARM result: its reference is sticky —
        max(seed reference, own balancedness) with its own (gate-bounded)
        violated set — so only cold solves re-anchor the gate (see
        WarmSeedStore.store). ONE implementation for the serial solve
        and the fleet-precompute write-back, so the never-degrade
        contract cannot diverge between the two paths."""
        if warm_accepted and seed is not None:
            self._warm_seeds.store(final_state, meta, result, reference=(
                max(seed.balancedness_after, result.balancedness_after),
                frozenset(result.violated_goals_after)))
        else:
            self._warm_seeds.store(final_state, meta, result)

    # -- megabatch precompute seams (fleet.megabatch) ----------------------
    def precompute_inputs(self):
        """(chain, state, meta, options, generation, initial_state) for a
        DEFAULT-chain cached-proposal computation — the megabatch
        runner's model-build seam. Mirrors ``proposals()``'s compute
        preamble exactly (same chain resolution, model requirements, and
        options generator), so a batched precompute stores a cache entry
        indistinguishable from a solo one. The generation is read BEFORE
        the build, like the serial path, so a mid-build metadata bump
        invalidates the entry rather than mislabeling it.

        Warm starts (round 18): with a valid seed, ``state`` is the
        warm-seeded search start and ``initial_state`` the TRUE current
        model the batched solve must diff against; the pending seed is
        held for ``store_precomputed``'s quality gate on the same worker
        thread. ``initial_state`` is None on cold computes."""
        gen = self._load_monitor.model_generation
        chain, state, meta = self._chain_and_model(None, False, None, True)
        options = self._options_generator.for_cached_proposal_calculation(
            meta.topic_names, ())
        initial = None
        self._tls_warm.ctx = None
        if self._warm_enabled:
            from .utils.sensors import SENSORS
            from .warmstart import apply_seed
            seed = self._warm_seeds.match(state, meta)
            self._tls_warm.ctx = (seed, state, meta, chain, options)
            if seed is not None:
                SENSORS.count("solver_warm_seeded")
                initial = state
                state = apply_seed(state, seed)
        return chain, state, meta, options, gen, initial

    def store_precomputed(self, generation: int, result,
                          final_state=None) -> None:
        """Write an externally computed default-chain OptimizerResult
        into the proposal cache (the megabatch runner's write-back seam —
        the batched twin of the cache store at the end of
        ``proposals()``). A warm-seeded precompute that falls below the
        sentry band is NOT stored: the seed is dropped, the fallback
        counted, and the cluster re-solved cold inline (on the runner's
        worker thread) — the same never-degrade contract as the serial
        warm path."""
        ctx = getattr(self._tls_warm, "ctx", None)
        self._tls_warm.ctx = None
        if ctx is not None:
            seed, initial, meta, chain, options = ctx
            warm_ok = seed is not None
            if seed is not None and not self._warm_quality_ok(result, seed):
                from .utils.sensors import SENSORS
                warm_ok = False
                SENSORS.count("solver_warm_fallbacks")
                self._warm_seeds.clear()
                LOG.info("warm-seeded precompute below the sentry band; "
                         "re-solving cold")
                final_state, result = self._optimizer.optimizations(
                    initial, meta, chain, options)
            if final_state is not None:
                self._warm_store(final_state, meta, result, seed=seed,
                                 warm_accepted=warm_ok)
        with self._proposal_lock:
            self._proposal_cache = (generation, time.time(), result)

    # -- predictive rebalancing (round 19) ---------------------------------
    def fix_predicted_violation(self, execute: bool = False,
                                reason: str = "",
                                anomaly_id: str | None = None) -> bool:
        """The PREDICTED_GOAL_VIOLATION fix: solve the forecaster's
        PROJECTED model — the current assignment under the horizon-peak
        loads, so proposals diff against the TRUE current state and are
        executable on the real cluster.

        ``execute=False`` (the default precompute mode) never moves
        anything:

        - the solve's compiled programs land on the exact jit cache keys
          the real fix will hit (same shape, same chain),
        - the predicted TARGET seeds the warm-seed store, so the real
          solve warm-starts from it (``solver.warm.start.enabled``
          consumes it; the store is written regardless so flipping warm
          on mid-incident still finds the seed), and
        - the fleet pacer is flagged (``predicted_precompute_pending``)
          to refresh this cluster's REAL proposal cache on its next
          sweep instead of waiting out the cadence.

        ``execute=True`` (the ``anomaly.detection.predictive.fix.enabled``
        opt-in) additionally EXECUTES the projected-model proposals —
        the proactive rebalance that heals before the violation.
        Returns True when a fix/precompute ran (the anomaly fix-started
        contract)."""
        from .utils.heal_ledger import current_heal
        from .utils.sensors import SENSORS
        last = self.forecast_engine.last_result
        if last is None:
            return False
        chain = self._goal_chain(None)
        # Same exclusion contract as the reactive goal-violation fix:
        # the self.healing.exclude.recently.* configs and the config's
        # never-move topics hold on the predictive path too.
        no_leadership = tuple(sorted(self.recently_demoted_brokers)) \
            if self._config.get_boolean(
                "self.healing.exclude.recently.demoted.brokers") else ()
        no_replicas = tuple(sorted(self.recently_removed_brokers)) \
            if self._config.get_boolean(
                "self.healing.exclude.recently.removed.brokers") else ()
        options = OptimizationOptions(
            excluded_brokers_for_leadership=no_leadership,
            excluded_brokers_for_replica_move=no_replicas,
            is_triggered_by_goal_violation=True)
        options = self._with_config_excluded_topics(last.meta, options)
        heal = current_heal()
        heal.phase("predictive_solve", horizonS=round(last.horizon_s, 3),
                   execute=bool(execute))
        final, result = self._optimize(last.projected_state, last.meta,
                                       chain, options)
        # The predicted target is the next solve's warm seed — but its
        # quality gate reference must describe REALITY, not the
        # projected model: a projected-model score can be optimistic
        # (warm attempts would spuriously fall back — one wasted solve)
        # or PESSIMISTIC (a too-low reference would let a degraded warm
        # result pass the sentry band — the round-18 cross-contamination
        # the incomparable-solve-class rule exists to prevent). Score
        # the predicted target against the CURRENT loads in one batched
        # entry snapshot and anchor the gate there.
        try:
            ref_state = dataclasses.replace(
                final, leader_load=last.state.leader_load,
                follower_load=last.state.follower_load)
            ref_chain, rv, _ro, _roff = self._optimizer.goal_entry_stats(
                ref_state, last.meta, chain, options)
            ref_violated = frozenset(
                g.name for g, v in zip(ref_chain, rv) if float(v) > 1e-6)
            reference = (self._optimizer.balancedness_of(ref_chain,
                                                         ref_violated),
                         ref_violated)
            self._warm_seeds.store(final, last.meta, result,
                                   reference=reference)
        except Exception:  # noqa: BLE001 — reference scoring is an
            # accuracy refinement; fall back to the solve's own quality
            LOG.debug("predicted-seed reference scoring failed",
                      exc_info=True)
            self._warm_seeds.store(final, last.meta, result)
        heal.phase("proposal_ready", predicted=True,
                   numProposals=len(result.proposals))
        if execute:
            executed = self._maybe_execute(
                result, dryrun=False, operation="predictive_rebalance",
                reason=reason or "proactive predicted-violation fix")
            if executed:
                SENSORS.count("anomaly_predicted_fixes")
                if anomaly_id is not None:
                    # The detector's settle pass distinguishes a
                    # prediction AVERTED by its own proactive fix
                    # (cleared) from one that plainly missed
                    # (self_cleared).
                    det = getattr(self, "predictive_detector", None)
                    if det is not None:
                        det.note_proactive_fix(anomaly_id)
                return True
            # Execution refused (executor busy / stop requested / zero
            # proposals): fall back to the precompute contract — the
            # prediction still leaves a hot answer and a pacer flag,
            # and the averted bookkeeping is correctly NOT marked.
        self.predicted_precompute_pending = True
        SENSORS.count("anomaly_predicted_precomputes")
        return True

    # Backwards-compatible precompute entry (the anomaly's default fix).
    def precompute_predicted(self) -> bool:
        return self.fix_predicted_violation(execute=False)

    def fix_slo_burn(self, objective: str = "", reason: str = "",
                     anomaly_id: str | None = None) -> bool:
        """The SLO_BURN fix: no rebalance to run — the burn is a serving
        condition, not an assignment problem — but the chain must reach
        FIX_STARTED and stay OPEN until the detector's budget-recovered
        terminal (returning False would close it ``fix_failed_to_start``
        and the clear would have no chain to land on). Mitigation is the
        precompute pacer flag: a hot proposal cache removes solve time
        from the request path, the one lever self-healing owns against a
        latency/shed burn. Returns True (the fix-started contract)."""
        from .utils.heal_ledger import current_heal
        from .utils.sensors import SENSORS
        current_heal().phase("mitigation_started", objective=objective,
                             reason=reason or "slo burn",
                             action="precompute_refresh")
        # Same lever as the predictive fix's precompute mode: the fleet
        # pacer refreshes this cluster's proposal cache on its next
        # sweep instead of waiting out the cadence.
        self.predicted_precompute_pending = True
        SENSORS.count("slo_burn_mitigations")
        return True

    def forecast_state(self, refresh: bool = False) -> dict:
        """GET /forecast body: the engine's last projection (per-broker
        current-vs-projected loads + confidence band) and the predictive
        detector's lifecycle counters. ``refresh=True`` fits a fresh
        forecast inline (device work — the param is explicit opt-in)."""
        eng = self.forecast_engine
        body: dict[str, Any] = {
            "forecastEnabled": eng.enabled,
            "horizonWindows": self._config.get_int(
                "forecast.horizon.windows"),
            "fitWindows": self._config.get_int("forecast.fit.windows"),
            "seasonalPeriodWindows": self._config.get_int(
                "forecast.seasonal.period.windows"),
            "predictiveFixEnabled": self._config.get_boolean(
                "anomaly.detection.predictive.fix.enabled"),
        }
        result = None
        if eng.enabled:
            # A refresh whose fresh fit is not ready yet (monitor short
            # of stable windows) falls back to the cached projection —
            # refresh means "at least as fresh as the cache", never
            # worse. A DISABLED engine serves null even if a pre-flip
            # fit is still cached (off means off).
            result = eng.forecast() if refresh else eng.last_result
            if result is None:
                result = eng.last_result
        body["forecast"] = result.to_dict() if result is not None else None
        det = getattr(self, "predictive_detector", None)
        body["detector"] = det.state() if det is not None else None
        return body

    # -- removal/demotion history (Executor.java retention parity) ---------
    def _history_now_ms(self) -> int:
        return self._now_ms() if self._now_ms is not None \
            else int(time.time() * 1000)

    def _history_active(self, hist: dict[int, int],
                        retention_ms: int) -> set[int]:
        """Prune expired entries and return the still-active broker ids."""
        now = self._history_now_ms()
        with self.excluded_sets_lock:
            for b in [b for b, ts in hist.items()
                      if now - ts > retention_ms]:
                del hist[b]
            return set(hist)

    def _history_record(self, hist: dict[int, int],
                        broker_ids: Sequence[int]) -> None:
        now = self._history_now_ms()
        with self.excluded_sets_lock:
            for b in broker_ids:
                hist[int(b)] = now

    @property
    def recently_removed_brokers(self) -> set[int]:
        """Brokers removed by an executed remove_brokers within the
        removal-history retention window — excluded as replica-move
        destinations by detection and exclude_recently_removed_brokers
        requests until the window (on the injected clock) lapses."""
        return self._history_active(self._removal_history,
                                    self._removal_retention_ms)

    @property
    def recently_demoted_brokers(self) -> set[int]:
        return self._history_active(self._demotion_history,
                                    self._demotion_retention_ms)

    def drop_recently_removed_brokers(self, broker_ids: Sequence[int]) -> None:
        with self.excluded_sets_lock:
            for b in broker_ids:
                self._removal_history.pop(int(b), None)

    def drop_recently_demoted_brokers(self, broker_ids: Sequence[int]) -> None:
        with self.excluded_sets_lock:
            for b in broker_ids:
                self._demotion_history.pop(int(b), None)

    @_traced_op("rebalance")
    def rebalance(self, goals: Sequence[str] | None = None, dryrun: bool = True,
                  ignore_proposal_cache: bool = False,
                  excluded_topics: Sequence[str] = (),
                  destination_broker_ids: Sequence[int] = (),
                  exclude_recently_demoted_brokers: bool = False,
                  exclude_recently_removed_brokers: bool = False,
                  is_triggered_by_user_request: bool = True,
                  use_ready_default_goals: bool = False,
                  fast_mode: bool = False,
                  data_from: str | None = None,
                  allow_capacity_estimation: bool = True,
                  reason: str = "", uuid: str = "") -> OperationResult:
        """RebalanceRunnable.workWithoutClusterModel:115."""
        del ignore_proposal_cache  # explicit model pass below is always fresh
        chain, state, meta = self._chain_and_model(
            goals, use_ready_default_goals, data_from,
            allow_capacity_estimation)
        # The history properties snapshot under the facade's lock.
        no_leadership = tuple(sorted(self.recently_demoted_brokers)) \
            if exclude_recently_demoted_brokers else ()
        no_replicas = tuple(sorted(self.recently_removed_brokers)) \
            if exclude_recently_removed_brokers else ()
        options = OptimizationOptions(
            excluded_topics=tuple(excluded_topics),
            excluded_brokers_for_leadership=no_leadership,
            excluded_brokers_for_replica_move=no_replicas,
            requested_destination_broker_ids=tuple(destination_broker_ids),
            is_triggered_by_goal_violation=not is_triggered_by_user_request,
            fast_mode=fast_mode)
        options = self._with_config_excluded_topics(meta, options)
        _final, result = self._optimize(state, meta, chain, options)
        executed = self._maybe_execute(result, dryrun, "rebalance", reason, uuid)
        return OperationResult("rebalance", dryrun, result, result.proposals,
                               executed, reason)

    @_traced_op("add_broker")
    def add_brokers(self, broker_ids: Sequence[int], dryrun: bool = True,
                    goals: Sequence[str] | None = None,
                    is_triggered_by_user_request: bool = True,
                    use_ready_default_goals: bool = False,
                    fast_mode: bool = False,
                    data_from: str | None = None,
                    allow_capacity_estimation: bool = True,
                    reason: str = "", uuid: str = "") -> OperationResult:
        """AddBrokersRunnable — mark NEW; the new-broker gate routes load
        onto them (ResourceDistributionGoal.rebalanceByMovingLoadIn:444)."""
        chain, state, meta = self._chain_and_model(
            goals, use_ready_default_goals, data_from,
            allow_capacity_estimation)
        state = self._mark_brokers(state, meta, broker_ids, BrokerState.NEW)
        options = self._with_config_excluded_topics(
            meta, OptimizationOptions(fast_mode=fast_mode))
        _final, result = self._optimize(state, meta, chain, options)
        executed = self._maybe_execute(result, dryrun, "add_broker", reason, uuid)
        if executed:
            # An added broker is a live destination again: clear any
            # removal-history entry so detection and
            # exclude_recently_removed_brokers requests stop excluding it
            # (AddBrokersRunnable drops re-added brokers from the
            # Executor's removal history).
            self.drop_recently_removed_brokers(broker_ids)
        return OperationResult("add_broker", dryrun, result, result.proposals,
                               executed, reason)

    @_traced_op("remove_broker")
    def remove_brokers(self, broker_ids: Sequence[int], dryrun: bool = True,
                       goals: Sequence[str] | None = None,
                       is_triggered_by_user_request: bool = True,
                       use_ready_default_goals: bool = False,
                       fast_mode: bool = False,
                       data_from: str | None = None,
                       allow_capacity_estimation: bool = True,
                       reason: str = "", uuid: str = "") -> OperationResult:
        """RemoveBrokersRunnable — mark DEAD so every replica they host
        becomes self-healing-eligible and must be relocated."""
        chain, state, meta = self._chain_and_model(
            goals, use_ready_default_goals, data_from,
            allow_capacity_estimation)
        state = self._mark_brokers(state, meta, broker_ids, BrokerState.DEAD)
        options = self._with_config_excluded_topics(
            meta, OptimizationOptions(
                excluded_brokers_for_replica_move=tuple(broker_ids),
                excluded_brokers_for_leadership=tuple(broker_ids),
                fast_mode=fast_mode))
        _final, result = self._optimize(state, meta, chain, options)
        executed = self._maybe_execute(result, dryrun, "remove_broker", reason, uuid)
        if executed:
            self._history_record(self._removal_history, broker_ids)
        return OperationResult("remove_broker", dryrun, result,
                               result.proposals, executed, reason)

    @_traced_op("demote_broker")
    def demote_brokers(self, broker_ids: Sequence[int], dryrun: bool = True,
                       is_triggered_by_user_request: bool = True,
                       skip_urp_demotion: bool = True,
                       exclude_follower_demotion: bool = False,
                       reason: str = "", uuid: str = "") -> OperationResult:
        """DemoteBrokerRunnable — PreferredLeaderElectionGoal with the
        demoted brokers excluded from leadership.

        ``skip_urp_demotion`` (default true, DemoteBrokerRunnable
        SKIP_URP_DEMOTION): partitions currently under-replicated are left
        alone. ``exclude_follower_demotion=False`` (the default) also
        reorders each affected partition's replica list so the demoted
        brokers' replicas come last (the reference's follower demotion);
        true limits the operation to leadership transfers."""
        from .analyzer.goals import PreferredLeaderElectionGoal
        state, meta = self._model()
        state = self._mark_brokers(state, meta, broker_ids, BrokerState.DEMOTED)
        options = OptimizationOptions(
            excluded_brokers_for_leadership=tuple(broker_ids))
        _final, result = self._optimizer.optimizations(
            state, meta, [PreferredLeaderElectionGoal()], options)
        proposals = list(result.proposals)
        parts = self._admin_call("admin.describe_partitions",
                                 self._admin.describe_partitions)
        if skip_urp_demotion:
            urp = {key for key, st in parts.items()
                   if set(st.replicas) - set(st.isr)}
            proposals = [p for p in proposals
                         if (p.topic, p.partition) not in urp]
        if not exclude_follower_demotion:
            demoted = set(broker_ids)
            covered = {(p.topic, p.partition): i
                       for i, p in enumerate(proposals)}
            for (topic, part), st in sorted(parts.items()):
                if skip_urp_demotion and set(st.replicas) - set(st.isr):
                    continue
                hit = [b for b in st.replicas if b in demoted]
                if not hit:
                    continue
                keep = [b for b in st.replicas if b not in demoted]
                reordered = tuple(keep + hit)
                idx = covered.get((topic, part))
                if idx is not None:
                    p0 = proposals[idx]
                    keep2 = [b for b in p0.new_replicas if b not in demoted]
                    hit2 = [b for b in p0.new_replicas if b in demoted]
                    proposals[idx] = dataclasses.replace(
                        p0, new_replicas=tuple(keep2 + hit2))
                elif reordered != tuple(st.replicas):
                    proposals.append(ExecutionProposal(
                        topic=topic, partition=part, old_leader=st.leader,
                        old_replicas=tuple(st.replicas),
                        new_replicas=reordered, new_leader=st.leader))
        result = dataclasses.replace(result, proposals=proposals)
        executed = self._maybe_execute(result, dryrun, "demote_broker", reason, uuid)
        if executed:
            self._history_record(self._demotion_history, broker_ids)
        return OperationResult("demote_broker", dryrun, result,
                               result.proposals, executed, reason)

    @_traced_op("fix_offline_replicas")
    def fix_offline_replicas(self, dryrun: bool = True,
                             goals: Sequence[str] | None = None,
                             is_triggered_by_user_request: bool = True,
                             use_ready_default_goals: bool = False,
                             fast_mode: bool = False,
                             data_from: str | None = None,
                             allow_capacity_estimation: bool = True,
                             reason: str = "", uuid: str = "") -> OperationResult:
        """FixOfflineReplicasRunnable — the model already marks replicas on
        dead brokers offline; the goal chain must relocate them."""
        chain, state, meta = self._chain_and_model(
            goals, use_ready_default_goals, data_from,
            allow_capacity_estimation)
        options = self._with_config_excluded_topics(
            meta, OptimizationOptions(only_move_immigrant_replicas=False,
                                      fast_mode=fast_mode))
        _final, result = self._optimize(state, meta, chain, options)
        executed = self._maybe_execute(result, dryrun, "fix_offline_replicas",
                                       reason, uuid)
        return OperationResult("fix_offline_replicas", dryrun, result,
                               result.proposals, executed, reason)

    @_traced_op("topic_configuration")
    def update_topic_replication_factor(self, topics: Sequence[str],
                                        replication_factor: int,
                                        dryrun: bool = True,
                                        is_triggered_by_user_request: bool = True,
                                        reason: str = "", uuid: str = "",
                                        skip_rack_awareness_check: bool = False,
                                        ) -> OperationResult:
        """UpdateTopicConfigurationRunnable — grow/shrink each partition's
        replica list to the target RF (rack-diverse, least-loaded brokers
        first for growth; drop the most-loaded non-leader for shrink)."""
        state, meta = self._model()
        want = set(topics)
        partitions = self._admin_call("admin.describe_partitions",
                                      self._admin.describe_partitions)
        alive = self._admin_call("admin.alive_brokers",
                                 self._admin.alive_brokers)
        racks = {bid: meta.rack_names[int(r)]
                 for bid, r in zip(meta.broker_ids, np.asarray(state.rack))}
        # populateRackInfoForReplicationFactorChange (RunnableUtils.java:74):
        # RF above the alive-broker count is always impossible; RF above the
        # rack count breaks one-replica-per-rack and needs the explicit
        # skip_rack_awareness_check opt-in.
        if replication_factor > len(alive):
            raise ValueError(
                f"replication factor {replication_factor} exceeds the "
                f"{len(alive)} alive broker(s)")
        if not skip_rack_awareness_check:
            num_racks = len({racks[b] for b in alive if b in racks})
            if replication_factor > max(num_racks, 1):
                raise ValueError(
                    f"replication factor {replication_factor} exceeds the "
                    f"{num_racks} distinct alive rack(s); pass "
                    "skip_rack_awareness_check=true to override")
        counts: dict[int, int] = {b: 0 for b in alive}
        for st in partitions.values():
            for b in st.replicas:
                counts[b] = counts.get(b, 0) + 1
        proposals: list[ExecutionProposal] = []
        for (topic, part), st in sorted(partitions.items()):
            if topic not in want or len(st.replicas) == replication_factor:
                continue
            old = tuple(st.replicas)
            leader = st.leader if st.leader is not None and st.leader >= 0 \
                else (old[0] if old else -1)
            new = list(old)
            while len(new) > replication_factor and len(new) > 1:
                victims = [b for b in new if b != leader] or new[1:]
                victim = max(victims, key=lambda b: counts.get(b, 0))
                new.remove(victim)
                counts[victim] = counts.get(victim, 0) - 1
            while len(new) < replication_factor:
                used_racks = {racks.get(b) for b in new}
                # Growth targets must be alive (a dead broker can appear in
                # stale replica lists and would otherwise win on count).
                candidates = [b for b in alive if b not in new]
                if not candidates:
                    break
                fresh = [b for b in candidates if racks.get(b) not in used_racks]
                pick = min(fresh or candidates, key=lambda b: counts.get(b, 0))
                new.append(pick)
                counts[pick] = counts.get(pick, 0) + 1
            if tuple(new) != old:
                proposals.append(ExecutionProposal(
                    topic=topic, partition=part, old_leader=leader,
                    old_replicas=old, new_replicas=tuple(new),
                    new_leader=leader))
        executed = False
        if proposals and not dryrun:
            self._executor.execute_proposals(proposals, uuid=uuid)
            executed = True
        return OperationResult("topic_configuration", dryrun, None,
                               tuple(proposals), executed, reason,
                               extra={"replicationFactor": replication_factor,
                                      "topics": sorted(want)})

    def _disk_model(self, state, meta):
        """(DiskTensors, DiskMeta) from the backend's JBOD surface, or raise
        when the backend is not JBOD-capable."""
        from .model.disks import build_disk_tensors
        replica_dirs_fn = getattr(self._admin, "replica_logdirs", None)
        logdirs_fn = getattr(self._admin, "describe_logdirs", None)
        if replica_dirs_fn is None or logdirs_fn is None or not logdirs_fn():
            raise ValueError(
                "operation requires a JBOD-capable admin backend "
                "(replica_logdirs/describe_logdirs)")
        return build_disk_tensors(state, meta, logdirs_fn(), replica_dirs_fn())

    def _intra_broker_result(self, operation, state, meta, disks0, disks1,
                             disk_meta, dryrun, reason) -> OperationResult:
        from .analyzer.proposals import ExecutionProposal
        from .model.disks import diff_intra_broker_moves
        moves = diff_intra_broker_moves(disks0, disks1, state, meta, disk_meta)
        executed = False
        if moves and not dryrun:
            # Submit through the Executor (intra-broker phase: per-broker
            # caps, completion polling, dead-task handling — Executor.java
            # :1672), NOT by calling the admin directly.
            from .common.resources import Resource
            disk_mb = np.asarray(state.leader_load[:, int(Resource.DISK)])
            row_of = {tp: i for i, tp in enumerate(meta.partition_index)}
            proposals = [ExecutionProposal(
                topic=m.topic, partition=m.partition, old_leader=-1,
                old_replicas=(), new_replicas=(), new_leader=-1,
                logdir_broker=m.broker_id, source_logdir=m.source_logdir,
                destination_logdir=m.destination_logdir,
                data_to_move_mb=float(disk_mb[row_of[(m.topic, m.partition)]])
                ) for m in moves]
            OPERATION_LOG.info("%s executing %d intra-broker moves "
                               "(reason: %s)", operation, len(moves), reason)
            self._executor.execute_proposals(proposals, uuid=operation)
            executed = True
        return OperationResult(
            operation, dryrun, executed=executed, reason=reason,
            extra={"intraBrokerMoves": [
                {"topic": m.topic, "partition": m.partition,
                 "broker": m.broker_id, "sourceLogdir": m.source_logdir,
                 "destinationLogdir": m.destination_logdir} for m in moves]})

    @_traced_op("remove_disks")
    def remove_disks(self, broker_logdirs: Mapping[int, Sequence[str]],
                     dryrun: bool = True, reason: str = "",
                     uuid: str = "") -> OperationResult:
        """RemoveDisksRunnable — mark the named log dirs dead in the disk
        model and drain them with the [B]-parallel intra-broker kernel
        (heaviest replicas first onto the least-utilized remaining dirs)."""
        import dataclasses as dc

        import jax.numpy as jnp

        from .analyzer.goals.intra_broker import IntraBrokerDiskCapacityGoal
        state, meta = self._model()
        disks, disk_meta = self._disk_model(state, meta)
        dead = np.asarray(disks.disk_alive).copy()
        requested = np.zeros_like(dead)  # dirs named in THIS request
        idx = {bid: i for i, bid in enumerate(meta.broker_ids)}
        for broker, dirs in broker_logdirs.items():
            if broker not in idx:
                raise ValueError(f"unknown broker {broker}")
            i = idx[broker]
            for d in dirs:
                if d not in disk_meta.dir_names[i]:
                    raise ValueError(f"broker {broker} has no log dir {d!r}")
                slot = disk_meta.dir_names[i].index(d)
                dead[i, slot] = False
                requested[i, slot] = True
            if not dead[i].any():
                raise ValueError(f"broker {broker}: no remaining alive log dirs")
        marked = dc.replace(disks, disk_alive=jnp.asarray(dead))
        movable = self._movable_partition_mask(state, meta)
        if movable is not None:
            # A pinned (never-move) replica on a dir being REMOVED BY THIS
            # REQUEST is an unresolvable conflict between the two
            # contracts: draining it violates the exclusion, leaving it
            # silently loses the replica when the operator pulls the disk.
            # Refuse loudly. Only dirs NAMED IN THIS REQUEST count — a
            # long-offline dir elsewhere must not block this operation
            # (and a named dir that was already offline still counts: the
            # operator is about to pull that disk).
            assign = np.asarray(disks.disk_assignment)
            broker_of = np.asarray(state.assignment)
            pinned = ~np.asarray(movable)
            removed_now = requested
            valid = (broker_of >= 0) & (assign >= 0)
            hit = pinned[:, None] & valid & removed_now[
                np.clip(broker_of, 0, None), np.clip(assign, 0, None)]
            stuck_rows = np.nonzero(hit.any(axis=1))[0]
            if stuck_rows.size:
                names = [meta.partition_index[p] if
                         p < len(meta.partition_index) else int(p)
                         for p in stuck_rows[:10]]
                raise ValueError(
                    f"excluded-topic replicas live on the removed log dirs "
                    f"and may not be moved "
                    f"(topics.excluded.from.partition.movement): {names}")
        balanced = IntraBrokerDiskCapacityGoal().optimize(
            state, marked, movable=movable)
        return self._intra_broker_result("remove_disks", state, meta, marked,
                                         balanced, disk_meta, dryrun, reason)

    @_traced_op("rebalance_disk")
    def rebalance_disk(self, dryrun: bool = True, reason: str = "",
                       uuid: str = "") -> OperationResult:
        """REBALANCE?rebalance_disk=true — intra-broker disk-usage balance
        (IntraBrokerDiskUsageDistributionGoal over every broker at once)."""
        from .analyzer.goals.intra_broker import (
            IntraBrokerDiskUsageDistributionGoal,
        )
        state, meta = self._model()
        disks, disk_meta = self._disk_model(state, meta)
        balanced = IntraBrokerDiskUsageDistributionGoal().optimize(
            state, disks, movable=self._movable_partition_mask(state, meta))
        return self._intra_broker_result("rebalance_disk", state, meta, disks,
                                         balanced, disk_meta, dryrun, reason)

    def rightsize(self, num_brokers_to_add: int = 0, partition_count: int = 0,
                  topic: str | None = None) -> OperationResult:
        """RightsizeRunnable — hand a ProvisionRecommendation to the
        configured Provisioner."""
        if not self._config.get_boolean("provisioner.enable"):
            raise ValueError(
                "provisioner is disabled (provisioner.enable=false)")
        from .detector.provisioner import ProvisionRecommendation, ProvisionStatus
        rec = ProvisionRecommendation(
            status=ProvisionStatus.UNDER_PROVISIONED,
            num_brokers=num_brokers_to_add, num_partitions=partition_count,
            topic=topic)
        state = self.provisioner.rightsize([rec])
        return OperationResult("rightsize", dryrun=False,
                               extra={"provisionerState": state.value,
                                      "recommendation": rec.to_dict()})

    # -- admin toggles ------------------------------------------------------
    def set_concurrency(self, inter_broker_per_broker: int | None = None,
                        intra_broker_per_broker: int | None = None,
                        leadership_cluster: int | None = None) -> dict:
        """ADMIN endpoint concurrency overrides."""
        return self._executor.set_requested_concurrency(
            inter_broker_per_broker=inter_broker_per_broker,
            intra_broker_per_broker=intra_broker_per_broker,
            leadership_cluster=leadership_cluster)

    def pause_metric_sampling(self, reason: str = "") -> None:
        self._load_monitor.pause_metric_sampling(reason)

    def resume_metric_sampling(self, reason: str = "") -> None:
        self._load_monitor.resume_metric_sampling(reason)

    def stop_proposal_execution(self, force_stop: bool = False,
                                stop_external_agent: bool = False) -> None:
        """STOP_PROPOSAL_EXECUTION (Executor.userTriggeredStopExecution:1139).
        ``force_stop`` is accepted for parameter parity — with the
        AdminClient (KIP-455) cancellation path both modes cancel in-flight
        reassignments, the old soft/force split only existed for ZK-based
        stops. ``stop_external_agent`` additionally cancels reassignments
        this executor did not start (maybeStopExternalAgent:1261) when no
        internal execution is running."""
        del force_stop
        self._executor.stop_execution()
        if stop_external_agent:
            cancelled = self._executor.stop_external_reassignments()
            if cancelled:
                OPERATION_LOG.info(
                    "stop_proposal_execution cancelled %d external "
                    "reassignment(s)", cancelled)

    # -- state (the STATE endpoint dashboard) -------------------------------
    def state(self, substates: Sequence[str] = (),
              super_verbose: bool = False) -> dict:
        """STATE body; ``super_verbose`` adds the per-window detail the
        reference's CruiseControlState verbose/super_verbose flags expose
        (monitored window timestamps, executor history)."""
        want = {s.lower() for s in substates} or \
            {"monitor", "executor", "analyzer", "anomaly_detector"}
        out: dict[str, Any] = {}
        # LoadMonitor.state() walks full partition metadata + completeness:
        # compute at most once per request (shared by monitor + analyzer).
        _ms_cache: list = []

        def monitor_state():
            if not _ms_cache:
                _ms_cache.append(self._load_monitor.state())
            return _ms_cache[0]

        def _ready_names():
            # Guarded: a not-yet-started monitor degrades readyGoals to []
            # instead of failing the whole STATE request.
            try:
                return self.ready_goals(monitor_state=monitor_state())
            except Exception:  # noqa: BLE001 — monitor not started yet
                return []

        if "monitor" in want:
            ms = monitor_state()
            out["MonitorState"] = {
                "state": ms.runner_state,
                "numValidWindows": ms.num_valid_windows,
                "monitoredWindows": ms.num_valid_windows,
                "monitoringCoveragePct": round(
                    100.0 * ms.monitored_partitions_percentage, 3),
                "totalNumPartitions": ms.total_num_partitions,
                "numPartitionSamples": ms.num_partition_samples,
                "modelGeneration": ms.model_generation,
            }
            if super_verbose:
                try:
                    out["MonitorState"]["windowTimestampsMs"] = \
                        self._load_monitor.window_times()
                except Exception:  # noqa: BLE001 — detail only
                    out["MonitorState"]["windowTimestampsMs"] = []
        if "executor" in want:
            out["ExecutorState"] = self._executor.execution_state(
                history_limit=20 if super_verbose else 5)
        if "analyzer" in want:
            with self._proposal_lock:
                cached = self._proposal_cache
            out["AnalyzerState"] = {
                "isProposalReady": cached is not None,
                "readyGoals": [g.name for g in _ready_names()],
                "balancednessScore":
                    self.goal_violation_detector.balancedness_score,
            }
            # Prewarm progress (round 18): how far the background
            # known-shape compile sweep has come — the signal a fresh
            # replica's readiness probe should watch before admitting
            # solver traffic. Absent when prewarm is disabled.
            from .warmstart import prewarm_status
            pw = prewarm_status(self._optimizer)
            if pw is not None:
                out["AnalyzerState"]["prewarm"] = pw
        if "anomaly_detector" in want:
            out["AnomalyDetectorState"] = self._anomaly_detector.state()
        return out
