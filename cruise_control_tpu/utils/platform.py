"""Host-platform control for tests and driver hooks.

The ambient environment may pin jax to a single-chip TPU tunnel (platform
"axon") via sitecustomize, which (a) can block for minutes while claiming
the chip and (b) can never provide more than one device.  Multi-device
code paths (``jax.sharding.Mesh`` over N devices) are therefore exercised
on the *virtual host-CPU platform*: ``--xla_force_host_platform_device_count``
splits the host CPU into N XLA devices.  This module is the single home of
that workaround (used by ``tests/conftest.py`` and
``__graft_entry__.dryrun_multichip``).
"""

from __future__ import annotations

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def force_host_cpu_devices(n: int):
    """Force jax onto the CPU platform with at least ``n`` virtual devices.

    Must run before the jax backend is first used in this process.  Sets the
    XLA flag (raising an existing smaller count to ``n``; an existing count
    >= ``n`` is kept), pins ``JAX_PLATFORMS=cpu`` both via env var and via a
    config update after import (sitecustomize may have overridden the env
    var with a config update of its own), then verifies the backend actually
    came up as CPU with enough devices — failing loudly here beats a
    confusing downstream mesh-construction error.

    Returns the imported ``jax`` module.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    match = re.search(_COUNT_FLAG + r"=(\d+)", flags)
    if match is None:
        os.environ["XLA_FLAGS"] = f"{flags} {_COUNT_FLAG}={n}".strip()
    elif int(match.group(1)) < n:
        os.environ["XLA_FLAGS"] = flags.replace(
            match.group(0), f"{_COUNT_FLAG}={n}")
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")
    devices = jax.devices()
    if devices[0].platform != "cpu" or len(devices) < n:
        raise RuntimeError(
            f"force_host_cpu_devices({n}) too late: the jax backend is "
            f"already initialized as {len(devices)} {devices[0].platform!r} "
            "device(s). Call it before any jax backend use in this process "
            "(e.g. before running entry()'s step).")
    return jax
