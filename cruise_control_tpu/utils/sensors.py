"""Self-instrumentation sensors → Prometheus text exposition.

Reference parity: the Dropwizard MetricRegistry → JMX domain
``kafka.cruisecontrol`` (KafkaCruiseControlApp.java:29-32) with ~40
operational sensors (docs/wiki/User Guide/Sensors.md: valid-windows,
monitored-partitions-percentage, balancedness-score,
proposal-computation-timer GoalOptimizer.java:128,
cluster-model-creation-timer LoadMonitor.java:177, execution
counts/timers Executor.java:145-148,346). JMX is a JVM-ism; the TPU-era
export surface is a Prometheus ``/metrics`` endpoint fed by the same
sensor registry.

Four metric kinds: counters, gauges, timers (count/sum/last/max — the
Dropwizard shape), and histograms (``observe``): log-spaced buckets
rendered as cumulative ``_bucket{le=...}`` series so latency
DISTRIBUTIONS survive aggregation — the timer shape collapses to
count/sum/last/max and no p99 can be recovered from it. The span tracer
(utils.tracing) feeds one histogram series per span name automatically.

Hot-path cost is one dict write per record — no locks on read-modify of
floats beyond a plain mutex, nothing device-side.
"""

from __future__ import annotations

import bisect
import contextvars
import threading
from contextlib import contextmanager

_PREFIX = "kafka_cruisecontrol"

# Log-spaced default histogram buckets (seconds): the 1-2.5-5 decade
# ladder from 1 ms to 60 s, covering everything from a span around a
# single device dispatch to a full 7k-broker chain solve.
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 60.0)

# Ambient per-cluster label (fleet federation): work executed on behalf of
# a registered cluster — a scheduler job, a ?cluster=-routed API request —
# runs inside ``cluster_label(cid)``, and every sensor written underneath
# picks up the ``cluster`` label without touching the call sites. Scoped
# via ContextVar so concurrent per-cluster work cannot mislabel each other.
_CLUSTER: contextvars.ContextVar[str | None] = \
    contextvars.ContextVar("sensor_cluster_label", default=None)


@contextmanager
def cluster_label(cluster_id: str | None):
    """Attribute all sensors recorded inside the block to ``cluster_id``
    (None = no-op, so call sites need no branching)."""
    token = _CLUSTER.set(cluster_id)
    try:
        yield
    finally:
        _CLUSTER.reset(token)


def current_cluster_label() -> str | None:
    return _CLUSTER.get()


def escape_label_value(value) -> str:
    """Prometheus text-format label escaping: backslash, double quote and
    newline must be escaped or the scrape line is syntactically broken
    (a single quoted value with an embedded ``"`` truncates the label
    set and corrupts every sample after it)."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


class _Histogram:
    """Per-series bucket counts. ``counts[i]`` is the NON-cumulative count
    of observations ≤ ``buckets[i]`` and > the previous bound;
    ``counts[-1]`` is the +Inf overflow. Cumulated at render time."""

    __slots__ = ("buckets", "counts", "total", "count")

    def __init__(self, buckets: tuple):
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)
        self.total = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        self.counts[bisect.bisect_left(self.buckets, value)] += 1
        self.total += value
        self.count += 1

    def quantile(self, q: float) -> float:
        return bucket_quantile(self.buckets, self.counts, q)


def bucket_quantile(buckets: tuple, counts: list, q: float) -> float:
    """Estimated q-quantile (0..1) over NON-cumulative bucket counts
    (+Inf overflow last), with linear interpolation inside the landing
    bucket (the Prometheus histogram_quantile estimate). The +Inf bucket
    clamps to the top finite bound. Edge cases are PINNED, never
    None/NaN: an empty window (all-zero counts, or no finite bounds)
    is 0.0; a single-bucket layout answers its one bound — the SLO
    engine's latency objectives call this hot and must get a number.
    Exposed standalone so callers holding snapshot DIFFS (per-stage
    bench windows) reuse the same math."""
    total = sum(counts)
    if total == 0 or not buckets:
        return 0.0
    if len(buckets) == 1:
        return float(buckets[0])
    rank = q * total
    cum = 0
    for i, c in enumerate(counts):
        cum += c
        if cum >= rank and c:
            if i >= len(buckets):
                return float(buckets[-1])
            lo = buckets[i - 1] if i else 0.0
            hi = buckets[i]
            return float(lo + (hi - lo) * (rank - (cum - c)) / c)
    return float(buckets[-1])


class SensorRegistry:
    """Counters, gauges, timers and histograms keyed by (name, labels)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[tuple[str, tuple], float] = {}
        self._gauges: dict[tuple[str, tuple], float] = {}
        # name -> (count, total_seconds, last_seconds, max_seconds)
        self._timers: dict[tuple[str, tuple], tuple[int, float, float, float]] = {}
        self._histograms: dict[tuple[str, tuple], _Histogram] = {}

    @staticmethod
    def _key(name: str, labels: dict | None) -> tuple[str, tuple]:
        cluster = _CLUSTER.get()
        if cluster is not None and "cluster" not in (labels or {}):
            labels = {**(labels or {}), "cluster": cluster}
        return name, tuple(sorted((labels or {}).items()))

    def count(self, name: str, value: float = 1.0,
              labels: dict | None = None) -> None:
        k = self._key(name, labels)
        with self._lock:
            self._counters[k] = self._counters.get(k, 0.0) + value

    def gauge(self, name: str, value: float, labels: dict | None = None) -> None:
        with self._lock:
            self._gauges[self._key(name, labels)] = float(value)

    def record_timer(self, name: str, seconds: float,
                     labels: dict | None = None) -> None:
        k = self._key(name, labels)
        with self._lock:
            count, total, _last, mx = self._timers.get(k, (0, 0.0, 0.0, 0.0))
            self._timers[k] = (count + 1, total + seconds, seconds,
                              max(mx, seconds))

    def observe(self, name: str, value: float, labels: dict | None = None,
                buckets: tuple | None = None) -> None:
        """Record into the histogram series ``(name, labels)``. The bucket
        layout is fixed by the FIRST observation of a series (Prometheus
        semantics: bucket bounds of a live series never change)."""
        k = self._key(name, labels)
        with self._lock:
            h = self._histograms.get(k)
            if h is None:
                h = self._histograms[k] = _Histogram(
                    tuple(buckets) if buckets else DEFAULT_BUCKETS)
            h.observe(value)

    def quantile(self, name: str, q: float,
                 labels: dict | None = None) -> float | None:
        """Estimated q-quantile of a histogram series (None ONLY when
        the series does not exist; an existing-but-empty window pins to
        0.0 via bucket_quantile) — the bench/CI summary hook for
        p50/p99 columns."""
        k = self._key(name, labels)
        with self._lock:
            h = self._histograms.get(k)
            return h.quantile(q) if h is not None else None

    def histogram_snapshot(self, name: str, labels: dict | None = None,
                           ) -> dict | None:
        """{buckets, counts (non-cumulative, +Inf last), sum, count} of a
        series, or None (test/introspection surface)."""
        k = self._key(name, labels)
        with self._lock:
            h = self._histograms.get(k)
            if h is None:
                return None
            return {"buckets": h.buckets, "counts": list(h.counts),
                    "sum": h.total, "count": h.count}

    def clear(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._timers.clear()
            self._histograms.clear()

    def remove_labeled(self, label: str, value: str) -> int:
        """Drop every series carrying ``label=value`` (fleet deregister:
        a removed cluster's series must disappear from the export, not
        freeze at their last values). Returns the number removed."""
        pair = (label, value)
        removed = 0
        with self._lock:
            for store in (self._counters, self._gauges, self._timers,
                          self._histograms):
                stale = [k for k in store if pair in k[1]]
                for k in stale:
                    del store[k]
                removed += len(stale)
        return removed

    # -- exposition --------------------------------------------------------
    @staticmethod
    def _labels_str(labels: tuple, extra: tuple = ()) -> str:
        pairs = labels + extra
        if not pairs:
            return ""
        inner = ",".join(f'{k}="{escape_label_value(v)}"' for k, v in pairs)
        return "{" + inner + "}"

    @classmethod
    def _fmt(cls, name: str, labels: tuple, value: float) -> str:
        return f"{_PREFIX}_{name}{cls._labels_str(labels)} {value}"

    @staticmethod
    def _type_line(lines: list[str], seen: set, family: str,
                   kind: str) -> None:
        if family not in seen:
            seen.add(family)
            lines.append(f"# TYPE {_PREFIX}_{family} {kind}")

    def render(self, extra_gauges: dict | None = None) -> str:
        """Prometheus text format. ``extra_gauges`` lets the scrape handler
        mix in live values (name -> value or (value, labels))."""
        lines: list[str] = []
        typed: set[str] = set()
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            timers = dict(self._timers)
            histograms = {k: (h.buckets, list(h.counts), h.total, h.count)
                          for k, h in self._histograms.items()}
        for name, value in (extra_gauges or {}).items():
            labels: dict | None = None
            if isinstance(value, tuple):
                value, labels = value
            gauges[self._key(name, labels)] = float(value)
        for (name, labels), v in sorted(counters.items()):
            self._type_line(lines, typed, name + "_total", "counter")
            lines.append(self._fmt(name + "_total", labels, v))
        for (name, labels), v in sorted(gauges.items()):
            self._type_line(lines, typed, name, "gauge")
            lines.append(self._fmt(name, labels, v))
        for (name, labels), (count, total, last, mx) in sorted(timers.items()):
            lines.append(self._fmt(name + "_seconds_count", labels, count))
            lines.append(self._fmt(name + "_seconds_sum", labels, total))
            lines.append(self._fmt(name + "_seconds_last", labels, last))
            lines.append(self._fmt(name + "_seconds_max", labels, mx))
        for (name, labels), (buckets, counts, total, count) in sorted(
                histograms.items()):
            self._type_line(lines, typed, name, "histogram")
            full = f"{_PREFIX}_{name}_bucket"
            cum = 0
            for bound, c in zip(buckets, counts):
                cum += c
                lines.append(full + self._labels_str(
                    labels, (("le", repr(float(bound))),)) + f" {cum}")
            lines.append(full + self._labels_str(
                labels, (("le", "+Inf"),)) + f" {count}")
            lines.append(self._fmt(name + "_sum", labels, total))
            lines.append(self._fmt(name + "_count", labels, count))
        return "\n".join(lines) + "\n"


SENSORS = SensorRegistry()
