"""Self-instrumentation sensors → Prometheus text exposition.

Reference parity: the Dropwizard MetricRegistry → JMX domain
``kafka.cruisecontrol`` (KafkaCruiseControlApp.java:29-32) with ~40
operational sensors (docs/wiki/User Guide/Sensors.md: valid-windows,
monitored-partitions-percentage, balancedness-score,
proposal-computation-timer GoalOptimizer.java:128,
cluster-model-creation-timer LoadMonitor.java:177, execution
counts/timers Executor.java:145-148,346). JMX is a JVM-ism; the TPU-era
export surface is a Prometheus ``/metrics`` endpoint fed by the same
sensor registry.

Hot-path cost is one dict write per record — no locks on read-modify of
floats beyond a plain mutex, nothing device-side.
"""

from __future__ import annotations

import threading

_PREFIX = "kafka_cruisecontrol"


class SensorRegistry:
    """Counters, gauges and timers keyed by (name, labels)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[tuple[str, tuple], float] = {}
        self._gauges: dict[tuple[str, tuple], float] = {}
        # name -> (count, total_seconds, last_seconds, max_seconds)
        self._timers: dict[tuple[str, tuple], tuple[int, float, float, float]] = {}

    @staticmethod
    def _key(name: str, labels: dict | None) -> tuple[str, tuple]:
        return name, tuple(sorted((labels or {}).items()))

    def count(self, name: str, value: float = 1.0,
              labels: dict | None = None) -> None:
        k = self._key(name, labels)
        with self._lock:
            self._counters[k] = self._counters.get(k, 0.0) + value

    def gauge(self, name: str, value: float, labels: dict | None = None) -> None:
        with self._lock:
            self._gauges[self._key(name, labels)] = float(value)

    def record_timer(self, name: str, seconds: float,
                     labels: dict | None = None) -> None:
        k = self._key(name, labels)
        with self._lock:
            count, total, _last, mx = self._timers.get(k, (0, 0.0, 0.0, 0.0))
            self._timers[k] = (count + 1, total + seconds, seconds,
                              max(mx, seconds))

    def clear(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._timers.clear()

    # -- exposition --------------------------------------------------------
    @staticmethod
    def _fmt(name: str, labels: tuple, value: float) -> str:
        full = f"{_PREFIX}_{name}"
        if labels:
            inner = ",".join(f'{k}="{v}"' for k, v in labels)
            full += "{" + inner + "}"
        return f"{full} {value}"

    def render(self, extra_gauges: dict | None = None) -> str:
        """Prometheus text format. ``extra_gauges`` lets the scrape handler
        mix in live values (name -> value or (value, labels))."""
        lines: list[str] = []
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            timers = dict(self._timers)
        for name, value in (extra_gauges or {}).items():
            labels: dict | None = None
            if isinstance(value, tuple):
                value, labels = value
            gauges[self._key(name, labels)] = float(value)
        for (name, labels), v in sorted(counters.items()):
            lines.append(self._fmt(name + "_total", labels, v))
        for (name, labels), v in sorted(gauges.items()):
            lines.append(self._fmt(name, labels, v))
        for (name, labels), (count, total, last, mx) in sorted(timers.items()):
            lines.append(self._fmt(name + "_seconds_count", labels, count))
            lines.append(self._fmt(name + "_seconds_sum", labels, total))
            lines.append(self._fmt(name + "_seconds_last", labels, last))
            lines.append(self._fmt(name + "_seconds_max", labels, mx))
        return "\n".join(lines) + "\n"


SENSORS = SensorRegistry()
