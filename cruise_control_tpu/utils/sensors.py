"""Self-instrumentation sensors → Prometheus text exposition.

Reference parity: the Dropwizard MetricRegistry → JMX domain
``kafka.cruisecontrol`` (KafkaCruiseControlApp.java:29-32) with ~40
operational sensors (docs/wiki/User Guide/Sensors.md: valid-windows,
monitored-partitions-percentage, balancedness-score,
proposal-computation-timer GoalOptimizer.java:128,
cluster-model-creation-timer LoadMonitor.java:177, execution
counts/timers Executor.java:145-148,346). JMX is a JVM-ism; the TPU-era
export surface is a Prometheus ``/metrics`` endpoint fed by the same
sensor registry.

Hot-path cost is one dict write per record — no locks on read-modify of
floats beyond a plain mutex, nothing device-side.
"""

from __future__ import annotations

import contextvars
import threading
from contextlib import contextmanager

_PREFIX = "kafka_cruisecontrol"

# Ambient per-cluster label (fleet federation): work executed on behalf of
# a registered cluster — a scheduler job, a ?cluster=-routed API request —
# runs inside ``cluster_label(cid)``, and every sensor written underneath
# picks up the ``cluster`` label without touching the call sites. Scoped
# via ContextVar so concurrent per-cluster work cannot mislabel each other.
_CLUSTER: contextvars.ContextVar[str | None] = \
    contextvars.ContextVar("sensor_cluster_label", default=None)


@contextmanager
def cluster_label(cluster_id: str | None):
    """Attribute all sensors recorded inside the block to ``cluster_id``
    (None = no-op, so call sites need no branching)."""
    token = _CLUSTER.set(cluster_id)
    try:
        yield
    finally:
        _CLUSTER.reset(token)


def current_cluster_label() -> str | None:
    return _CLUSTER.get()


class SensorRegistry:
    """Counters, gauges and timers keyed by (name, labels)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[tuple[str, tuple], float] = {}
        self._gauges: dict[tuple[str, tuple], float] = {}
        # name -> (count, total_seconds, last_seconds, max_seconds)
        self._timers: dict[tuple[str, tuple], tuple[int, float, float, float]] = {}

    @staticmethod
    def _key(name: str, labels: dict | None) -> tuple[str, tuple]:
        cluster = _CLUSTER.get()
        if cluster is not None and "cluster" not in (labels or {}):
            labels = {**(labels or {}), "cluster": cluster}
        return name, tuple(sorted((labels or {}).items()))

    def count(self, name: str, value: float = 1.0,
              labels: dict | None = None) -> None:
        k = self._key(name, labels)
        with self._lock:
            self._counters[k] = self._counters.get(k, 0.0) + value

    def gauge(self, name: str, value: float, labels: dict | None = None) -> None:
        with self._lock:
            self._gauges[self._key(name, labels)] = float(value)

    def record_timer(self, name: str, seconds: float,
                     labels: dict | None = None) -> None:
        k = self._key(name, labels)
        with self._lock:
            count, total, _last, mx = self._timers.get(k, (0, 0.0, 0.0, 0.0))
            self._timers[k] = (count + 1, total + seconds, seconds,
                              max(mx, seconds))

    def clear(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._timers.clear()

    def remove_labeled(self, label: str, value: str) -> int:
        """Drop every series carrying ``label=value`` (fleet deregister:
        a removed cluster's series must disappear from the export, not
        freeze at their last values). Returns the number removed."""
        pair = (label, value)
        removed = 0
        with self._lock:
            for store in (self._counters, self._gauges, self._timers):
                stale = [k for k in store if pair in k[1]]
                for k in stale:
                    del store[k]
                removed += len(stale)
        return removed

    # -- exposition --------------------------------------------------------
    @staticmethod
    def _fmt(name: str, labels: tuple, value: float) -> str:
        full = f"{_PREFIX}_{name}"
        if labels:
            inner = ",".join(f'{k}="{v}"' for k, v in labels)
            full += "{" + inner + "}"
        return f"{full} {value}"

    def render(self, extra_gauges: dict | None = None) -> str:
        """Prometheus text format. ``extra_gauges`` lets the scrape handler
        mix in live values (name -> value or (value, labels))."""
        lines: list[str] = []
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            timers = dict(self._timers)
        for name, value in (extra_gauges or {}).items():
            labels: dict | None = None
            if isinstance(value, tuple):
                value, labels = value
            gauges[self._key(name, labels)] = float(value)
        for (name, labels), v in sorted(counters.items()):
            lines.append(self._fmt(name + "_total", labels, v))
        for (name, labels), v in sorted(gauges.items()):
            lines.append(self._fmt(name, labels, v))
        for (name, labels), (count, total, last, mx) in sorted(timers.items()):
            lines.append(self._fmt(name + "_seconds_count", labels, count))
            lines.append(self._fmt(name + "_seconds_sum", labels, total))
            lines.append(self._fmt(name + "_seconds_last", labels, last))
            lines.append(self._fmt(name + "_seconds_max", labels, mx))
        return "\n".join(lines) + "\n"


SENSORS = SensorRegistry()
