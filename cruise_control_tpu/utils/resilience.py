"""Unified resilience layer: retry/backoff, circuit breaking, wrappers.

The reference survives a 7k-broker production fleet because every
external interaction is allowed to fail: AdminClient calls time out,
samplers drop intervals, brokers flap — and the JVM stack retries,
degrades, or isolates. This module is the TPU-era equivalent, one
policy object + one breaker shared by every boundary in the pipeline
(sampling fetch, metadata/admin calls, reassignment submission, fleet
jobs, detector runs):

- ``RetryPolicy``: exponential backoff with DETERMINISTIC seeded jitter
  (``crc32(seed:op:attempt)`` — two runs with the same seed produce
  byte-identical backoff schedules, so chaos tests assert exact retry
  timing with no statistical slack) and an overall deadline measured on
  an injectable clock (no ``time.sleep`` dependence in tests).
- ``CircuitBreaker``: per-target closed → open → half-open state
  machine keyed by any string (broker id, cluster id, backend op).
  Open targets fail fast with ``BreakerOpenError`` carrying the
  remaining recovery time (the API layer turns it into
  503 + ``Retry-After``).
- ``call_with_resilience``: the one wrapper call sites use. Emits
  ``retry_attempts_total{op=}`` / ``breaker_state{target=}`` sensors
  and opens a ``resilience.retry`` child span per RE-attempt so every
  retry is visible in ``GET /kafkacruisecontrol/trace``. The happy
  path (no policy, no breaker) is a single branch + direct call —
  guarded ns-scale by bench.py's ``resilience_noop_overhead``.
"""

from __future__ import annotations

import dataclasses
import enum
import threading
import time
import zlib
from typing import Callable

_U32 = float(0xFFFFFFFF)


class BreakerState(enum.IntEnum):
    """Gauge-friendly encoding (breaker_state{target=} exports the int)."""

    CLOSED = 0
    OPEN = 1
    HALF_OPEN = 2


class BreakerOpenError(RuntimeError):
    """Fail-fast refusal: the target's breaker is open. ``retry_after_s``
    is the remaining recovery window (API layer: 503 + Retry-After)."""

    def __init__(self, target: str, retry_after_s: float):
        super().__init__(
            f"circuit breaker open for {target!r}; retry in "
            f"{retry_after_s:.1f}s")
        self.target = target
        self.retry_after_s = retry_after_s


def default_retryable(exc: BaseException) -> bool:
    """Transient-error classification: connection/timeout/OS errors and
    anything self-declaring ``transient=True`` (the chaos faults, the
    wire client's protocol-retriable errors) retry; programming errors
    (ValueError, KeyError, ...) never do."""
    return isinstance(exc, (ConnectionError, TimeoutError, OSError)) \
        or bool(getattr(exc, "transient", False))


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff + deterministic seeded jitter + deadlines.

    ``backoff_s(op, attempt)`` is a pure function of (policy, op,
    attempt): jitter comes from ``crc32`` over the seed, not a PRNG
    stream, so concurrent call sites cannot perturb each other and a
    chaos run replays identically under the same seed.
    """

    max_attempts: int = 5
    base_backoff_s: float = 0.1
    max_backoff_s: float = 10.0
    multiplier: float = 2.0
    jitter_ratio: float = 0.2
    seed: int = 0
    overall_deadline_s: float = 60.0
    retryable: Callable[[BaseException], bool] = default_retryable

    @classmethod
    def from_config(cls, config) -> "RetryPolicy | None":
        """The ``resilience.retry.*`` keys; None when the layer is
        disabled (call sites then run bare — the no-op fast path)."""
        if not config.get_boolean("resilience.enabled"):
            return None
        return cls(
            max_attempts=config.get_int("resilience.retry.max.attempts"),
            base_backoff_s=config.get_long(
                "resilience.retry.base.backoff.ms") / 1000.0,
            max_backoff_s=config.get_long(
                "resilience.retry.max.backoff.ms") / 1000.0,
            multiplier=config.get_double(
                "resilience.retry.backoff.multiplier"),
            jitter_ratio=config.get_double("resilience.retry.jitter.ratio"),
            seed=config.get_int("resilience.retry.seed"),
            overall_deadline_s=config.get_long(
                "resilience.retry.overall.deadline.ms") / 1000.0)

    def backoff_s(self, op: str, attempt: int) -> float:
        """Backoff before re-attempt number ``attempt`` (first retry =
        attempt 2). Jitter SUBTRACTS up to ``jitter_ratio`` of the base
        so the result never exceeds the exponential envelope."""
        exp = min(self.max_backoff_s,
                  self.base_backoff_s * self.multiplier ** max(0, attempt - 2))
        if self.jitter_ratio <= 0:
            return exp
        u = zlib.crc32(f"{self.seed}:{op}:{attempt}".encode()) / _U32
        return exp * (1.0 - self.jitter_ratio * u)


@dataclasses.dataclass
class _Target:
    state: BreakerState = BreakerState.CLOSED
    consecutive_failures: int = 0
    opened_at: float = 0.0


class CircuitBreaker:
    """Per-target breaker map: closed → open after N consecutive
    failures, open → half-open after the recovery window, half-open →
    closed on the probe's success / back to open on its failure.

    ``clock`` is injectable (monotonic seconds) so every transition is
    testable without real waiting. ``failure_threshold <= 0`` disables
    the breaker entirely (``allow`` is always True, nothing recorded).
    """

    def __init__(self, failure_threshold: int = 5, recovery_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic,
                 name: str = "default"):
        self.failure_threshold = failure_threshold
        self.recovery_s = recovery_s
        self._clock = clock
        self._name = name
        self._lock = threading.Lock()
        self._targets: dict[str, _Target] = {}

    @classmethod
    def from_config(cls, config, name: str = "default",
                    clock: Callable[[], float] = time.monotonic,
                    ) -> "CircuitBreaker | None":
        if not config.get_boolean("resilience.enabled"):
            return None
        return cls(
            failure_threshold=config.get_int(
                "resilience.breaker.failure.threshold"),
            recovery_s=config.get_long(
                "resilience.breaker.recovery.ms") / 1000.0,
            clock=clock, name=name)

    @property
    def enabled(self) -> bool:
        return self.failure_threshold > 0

    def _entry(self, target: str) -> _Target:
        t = self._targets.get(target)
        if t is None:
            t = self._targets[target] = _Target()
        return t

    def _set_state(self, target: str, t: _Target, state: BreakerState) -> None:
        if t.state is state:
            return
        t.state = state
        from .sensors import SENSORS
        SENSORS.gauge("breaker_state", int(state),
                      labels={"breaker": self._name, "target": target})
        SENSORS.count("breaker_transitions",
                      labels={"breaker": self._name, "target": target,
                              "to": state.name})

    def state(self, target: str) -> BreakerState:
        with self._lock:
            return self._targets.get(target, _Target()).state

    def allow(self, target: str) -> bool:
        """True when a call to ``target`` may proceed. An open target
        whose recovery window elapsed flips to half-open and the call
        proceeds as the probe (single-consumer call sites — the fleet
        worker, the detector scheduler — probe one at a time by
        construction; concurrent probes are harmless, the first result
        decides)."""
        if not self.enabled:
            return True
        with self._lock:
            t = self._targets.get(target)
            if t is None or t.state is BreakerState.CLOSED:
                return True
            if t.state is BreakerState.OPEN:
                if self._clock() - t.opened_at < self.recovery_s:
                    return False
                self._set_state(target, t, BreakerState.HALF_OPEN)
            return True  # half-open: probe allowed

    def retry_after_s(self, target: str) -> float:
        """Remaining recovery window (0 when not open)."""
        with self._lock:
            t = self._targets.get(target)
            if t is None or t.state is not BreakerState.OPEN:
                return 0.0
            return max(0.0, self.recovery_s - (self._clock() - t.opened_at))

    def record_success(self, target: str) -> None:
        if not self.enabled:
            return
        with self._lock:
            t = self._entry(target)
            t.consecutive_failures = 0
            self._set_state(target, t, BreakerState.CLOSED)

    def record_failure(self, target: str) -> None:
        if not self.enabled:
            return
        with self._lock:
            t = self._entry(target)
            t.consecutive_failures += 1
            if t.state is BreakerState.HALF_OPEN \
                    or t.consecutive_failures >= self.failure_threshold:
                # A failed half-open probe re-opens with a fresh window.
                t.opened_at = self._clock()
                self._set_state(target, t, BreakerState.OPEN)

    def guard(self, target: str) -> None:
        """Raise BreakerOpenError when the target is open (the fail-fast
        entry check call sites use before expensive work)."""
        if not self.allow(target):
            raise BreakerOpenError(target, self.retry_after_s(target))


def call_with_resilience(op: str, fn: Callable, *,
                         policy: RetryPolicy | None = None,
                         breaker: CircuitBreaker | None = None,
                         target: str | None = None,
                         clock: Callable[[], float] = time.monotonic,
                         sleep: Callable[[float], None] = time.sleep):
    """Run ``fn()`` under the retry policy and/or breaker.

    - No policy and no breaker: direct call (the disabled fast path —
      one tuple compare, nothing else; bench-guarded).
    - Breaker (keyed by ``target``, default ``op``): open targets raise
      ``BreakerOpenError`` without calling ``fn``; every outcome is
      recorded.
    - Policy: retryable failures back off (``sleep`` injectable) and
      re-attempt until attempts or the overall deadline run out; each
      RE-attempt records ``retry_attempts_total{op=}`` and runs inside
      a ``resilience.retry`` span so traces show exactly where time
      went. The last failure propagates unchanged.
    """
    if policy is None and breaker is None:
        return fn()
    key = target if target is not None else op
    if breaker is not None:
        breaker.guard(key)
    max_attempts = policy.max_attempts if policy is not None else 1
    deadline = clock() + policy.overall_deadline_s \
        if policy is not None else None
    attempt = 1
    while True:
        try:
            if attempt == 1:
                result = fn()
            else:
                from .sensors import SENSORS
                from .tracing import TRACER
                SENSORS.count("retry_attempts", labels={"op": op})
                with TRACER.span("resilience.retry", operation=op,
                                 attempt=attempt):
                    result = fn()
        except BaseException as exc:  # noqa: BLE001 — classified below
            if breaker is not None:
                breaker.record_failure(key)
            retryable = policy is not None and policy.retryable(exc)
            if not retryable or attempt >= max_attempts:
                if policy is not None and attempt >= max_attempts:
                    from .sensors import SENSORS
                    SENSORS.count("retry_exhausted", labels={"op": op})
                raise
            backoff = policy.backoff_s(op, attempt + 1)
            if deadline is not None and clock() + backoff > deadline:
                from .sensors import SENSORS
                SENSORS.count("retry_deadline_exceeded", labels={"op": op})
                raise
            from .tracing import TRACER
            TRACER.annotate(retry_backoff_s=round(backoff, 4))
            sleep(backoff)
            attempt += 1
            continue
        if breaker is not None:
            breaker.record_success(key)
        return result


def with_resilience(op: str, *, policy: RetryPolicy | None = None,
                    breaker: CircuitBreaker | None = None,
                    target: str | None = None,
                    clock: Callable[[], float] = time.monotonic,
                    sleep: Callable[[float], None] = time.sleep):
    """Decorator form of ``call_with_resilience`` for module-level
    functions/methods with a fixed op name."""
    def deco(fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return call_with_resilience(
                op, lambda: fn(*args, **kwargs), policy=policy,
                breaker=breaker, target=target, clock=clock, sleep=sleep)
        return wrapper
    return deco
