"""XLA / device telemetry → the sensor registry.

The two PRs before this one created exactly the blind spots this module
covers: fleet shape-bucketing exists to stop recompile churn, and the
incremental model pipeline exists to cut host→device transfer — yet
nothing measured compile events, transfer bytes, or device memory, so
neither fix could be proven live. Three surfaces, all flowing into the
same ``/metrics`` scrape (ambient per-cluster labels apply):

- **Compilation**: ``jax.monitoring`` event listeners record every XLA
  backend compile (count + seconds, histogram ``xla_compile_seconds``)
  and persistent-cache hits/misses. Compiles are labeled with the padded
  bucket shape ambient at dispatch time (``shape_scope``), so a
  shape-flap recompile storm shows up as new ``shape=`` series — proving
  or disproving the bucket-hysteresis fix.
- **Device memory**: ``device_memory_bytes{device,kind}`` gauges from
  ``Device.memory_stats()`` (TPU/GPU allocator stats), refreshed at
  scrape time. Backends without allocator stats (CPU) fall back to the
  live jax.Array footprint so the series exists everywhere.
- **Transfers**: ``record_transfer()`` counts host↔device bytes at the
  call sites that move model data (the refresh pipeline's fused
  ``device_put``), and annotates the ambient trace span.

JAX-version caveats (documented in docs/DESIGN.md): the monitoring event
names are jax-internal strings — ``install()`` matches by suffix so a
rename degrades to missing series, never an exception; listeners cannot
be unregistered on this jax line, so install is once-per-process and
``enabled`` is checked inside the callbacks.
"""

from __future__ import annotations

import contextvars
import logging
import threading
from contextlib import contextmanager

from .sensors import SENSORS

LOG = logging.getLogger(__name__)

# Compile times span ~3 decades beyond span latencies: a warm small-shape
# compile is ~50 ms, a cold 7k-broker chain compile is minutes.
COMPILE_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 60.0,
                   150.0, 300.0, 600.0)

# The padded bucket shape whose dispatch is currently executing, e.g.
# "p102400_b1024" (set by GoalOptimizer around the solve): compiles fire
# from inside jit tracing, so a contextvar is the only way to attribute
# them to a model shape without threading labels through jax.
_SHAPE: contextvars.ContextVar[str | None] = \
    contextvars.ContextVar("xla_shape_label", default=None)

_BACKEND_COMPILE_SUFFIX = "backend_compile_duration"
_EVENT_COUNTERS = {
    "/jax/compilation_cache/cache_hits": "xla_compile_cache_hits",
    "/jax/compilation_cache/cache_misses": "xla_compile_cache_misses",
}

_install_lock = threading.Lock()
_installed = False
_enabled = True


@contextmanager
def shape_scope(num_partitions: int, num_brokers: int):
    """Label XLA compiles fired under this block with the padded model
    shape (the solver's compiled-kernel identity)."""
    token = _SHAPE.set(f"p{num_partitions}_b{num_brokers}")
    try:
        yield
    finally:
        _SHAPE.reset(token)


def _on_event_duration(event: str, duration_secs: float, **kwargs) -> None:
    if not _enabled:
        return
    try:
        if event.endswith(_BACKEND_COMPILE_SUFFIX):
            labels = {"shape": _SHAPE.get() or "unscoped"}
            SENSORS.count("xla_compile_events", labels=labels)
            # Histogram ONLY — a timer named xla_compile would render the
            # same xla_compile_seconds_sum/_count family twice and
            # Prometheus rejects duplicate-sample scrapes outright.
            SENSORS.observe("xla_compile_seconds", duration_secs,
                            labels=labels, buckets=COMPILE_BUCKETS)
        elif event.endswith("cache_retrieval_time_sec"):
            # Persistent-cache hit: the retrieval that REPLACED a compile.
            SENSORS.observe("xla_compile_cache_retrieval_seconds",
                            duration_secs, buckets=COMPILE_BUCKETS)
        elif event.endswith("compile_time_saved_sec"):
            SENSORS.count("xla_compile_seconds_saved",
                          max(0.0, duration_secs))
    except Exception:  # noqa: BLE001 — a telemetry bug must never break jit
        LOG.debug("xla telemetry listener failed", exc_info=True)


def _on_event(event: str, **kwargs) -> None:
    if not _enabled:
        return
    name = _EVENT_COUNTERS.get(event)
    if name is not None:
        SENSORS.count(name)


def install(enabled: bool = True) -> bool:
    """Register the jax.monitoring listeners (idempotent: jax keeps a
    plain listener list with no dedup, and this jax line has no public
    unregister — so install once and gate the callbacks on ``enabled``).
    Returns True when the listeners are active."""
    global _installed, _enabled
    with _install_lock:
        _enabled = bool(enabled)
        if _installed or not _enabled:
            return _installed
        try:
            from jax import monitoring
            monitoring.register_event_duration_secs_listener(
                _on_event_duration)
            monitoring.register_event_listener(_on_event)
        except Exception:  # noqa: BLE001 — older/newer jax without the API
            LOG.warning("jax.monitoring unavailable; xla telemetry off",
                        exc_info=True)
            return False
        _installed = True
        return True


# Rounds-per-dispatch are megastep budgets: pow2-ish from 1 to the
# AdaptiveDispatch ceiling (1024).
DISPATCH_ROUND_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0,
                          256.0, 512.0, 1024.0)


def record_dispatch(kind: str, rounds: int, donated: bool = False,
                    speculative: bool = False) -> None:
    """Account one solver device dispatch (a bounded megastep or a fused
    whole-pass execution): counters by kind (move/swap/chain), donation
    and speculative (async post-convergence no-op) tallies, and the
    rounds-per-dispatch histogram the bench reads its p50 from. The
    ambient trace span (goal.solve) gets a dispatch tally so traces show
    how many XLA executions a goal cost."""
    from .tracing import TRACER
    span = TRACER.current_span()
    if span is not None:
        span.attributes["dispatches"] = \
            int(span.attributes.get("dispatches", 0)) + 1
    if not _enabled:
        return
    labels = {"kind": kind}
    SENSORS.count("solver_dispatches", labels=labels)
    SENSORS.observe("solver_dispatch_rounds", float(rounds), labels=labels,
                    buckets=DISPATCH_ROUND_BUCKETS)
    if donated:
        SENSORS.count("solver_dispatch_donations", labels=labels)
    if speculative:
        SENSORS.count("solver_dispatch_speculative", labels=labels)


def record_transfer(nbytes: int, direction: str = "h2d",
                    source: str = "model_refresh") -> None:
    """Account one host↔device transfer: counters + the ambient span's
    ``transfer_bytes`` attribute (so a trace shows what the model refresh
    actually shipped). The span attribute belongs to the TRACING flag,
    the counters to this module's — each off switch removes its own
    surface and only that."""
    from .tracing import TRACER
    span = TRACER.current_span()
    if span is not None:
        span.attributes["transfer_bytes"] = \
            int(span.attributes.get("transfer_bytes", 0)) + int(nbytes)
    if not _enabled:
        return
    labels = {"direction": direction, "source": source}
    SENSORS.count("device_transfer_bytes", float(nbytes), labels=labels)
    SENSORS.count("device_transfers", labels=labels)


def refresh_device_gauges() -> None:
    """Refresh ``device_memory_bytes{device,kind}`` from the live backend
    (called at /metrics scrape time; gauges persist between scrapes).
    Allocator stats where the runtime provides them; otherwise the summed
    live jax.Array footprint per device, so the series is never absent
    just because the backend is host-local. No-op (no device polling, no
    live-array walk) when xla.telemetry.enabled=false."""
    if not _enabled:
        return
    try:
        import jax
        devices = jax.local_devices()
    except Exception:  # noqa: BLE001 — no backend, no gauges
        return
    stats_by_device = {}
    for d in devices:
        try:
            stats_by_device[d] = d.memory_stats()
        except Exception:  # noqa: BLE001 — unsupported on this runtime
            stats_by_device[d] = None
    if any(s is None for s in stats_by_device.values()):
        live: dict = {}
        try:
            for arr in jax.live_arrays():
                for d in getattr(arr, "devices", lambda: ())() or ():
                    live[d] = live.get(d, 0) + getattr(arr, "nbytes", 0)
        except Exception:  # noqa: BLE001 — live_arrays is debug API
            live = {}
        for d, s in stats_by_device.items():
            if s is None:
                stats_by_device[d] = {"bytes_in_use": live.get(d, 0)}
    for d, stats in stats_by_device.items():
        dev = f"{d.platform}:{d.id}"
        for kind in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                     "largest_free_block_bytes"):
            if stats and kind in stats:
                SENSORS.gauge("device_memory_bytes", float(stats[kind]),
                              labels={"device": dev, "kind": kind})
