"""Declarative SLO registry with multi-window burn-rate evaluation.

The production side of ScenarioScore's SLO floors (ROADMAP items 4/5):
a registry of service-level objectives — latency-quantile, error-rate,
shed-rate, staleness-age, time-to-heal — each a budgeted bad-event
fraction evaluated over sliding multi-window counters on the injectable
clock. Burn rate is the Google SRE Workbook definition:

    burn(window) = bad_fraction(window) / budget

so burn 1.0 spends the budget exactly at the objective period's pace,
and multi-window alerting (fast 5m/1h AND slow 30m/6h pairs both over
threshold) turns a standing burn into ONE low-flap signal —
``detector/slo_burn.py`` raises it as a first-class heal-ledger-tracked
anomaly.

Event-based windows: a window holds the events whose record time falls
inside it; no events → burn 0.0 (never NaN). Exposed as
``slo_error_budget_remaining{objective}`` /
``slo_burn_rate{objective,window}`` gauges and ``GET /slo``.

The SAME module evaluates the twin's floors:
``scenario_floor_violations`` renders ScenarioScore's verdict strings
byte-identically (testing/simulator.py delegates), so twin and
production share one SLO definition.

Off-means-off: a disabled registry's ``record*`` hooks return
immediately (benched as ``slo_noop_overhead``); observation never
changes behavior. Deterministic machinery (CCSA004): all timestamps
ride the injected ``clock`` seam.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Callable, Mapping

from .sensors import SENSORS

#: (fast, fast-confirm, slow, slow-confirm) window lengths in seconds —
#: the SRE Workbook's 5m/1h + 30m/6h multi-window pairs.
DEFAULT_WINDOWS_S = (300.0, 3600.0, 1800.0, 21600.0)

#: Objective kinds the registry understands. latency/staleness/heal are
#: threshold-classified durations; error/shed classify by status.
OBJECTIVE_KINDS = ("latency", "error", "shed", "staleness", "heal")

#: Events older than the longest window plus this slack are pruned.
_PRUNE_SLACK_S = 60.0

#: Per-objective event-ring bound (a backstop above any realistic rate;
#: windows prune by age first).
_MAX_EVENTS = 65536


@dataclasses.dataclass(frozen=True)
class Objective:
    """One declarative objective: ``budget`` is the allowed bad-event
    fraction; ``threshold_s`` classifies duration-kind events;
    ``quantile`` is the latency objective's reporting quantile."""

    name: str
    kind: str
    budget: float
    threshold_s: float = 0.0
    quantile: float = 0.99


class SloRegistry:
    """Sliding multi-window good/bad counters per objective.

    ``record_request`` classifies one front-door response into every
    request-kind objective; ``observe_staleness`` / ``observe_heal``
    feed the age/duration objectives from their own seams. ``evaluate``
    computes per-window burn rates + remaining budget and mirrors them
    into the sensor registry; ``burning`` applies the multi-window
    alert rule."""

    def __init__(self, objectives: list[Objective] | None = None,
                 enabled: bool = True,
                 windows_s: tuple = DEFAULT_WINDOWS_S,
                 fast_threshold: float = 14.4,
                 slow_threshold: float = 6.0,
                 clock: Callable[[], float] = time.time):
        self._enabled = bool(enabled)
        self._clock = clock
        self._windows = tuple(float(w) for w in windows_s)
        if len(self._windows) != 4:
            raise ValueError("windows_s must be (fast, fast_confirm, "
                             "slow, slow_confirm)")
        self.fast_threshold = float(fast_threshold)
        self.slow_threshold = float(slow_threshold)
        self._lock = threading.Lock()
        self._objectives: dict[str, Objective] = {}
        # name -> deque[(t, bad: bool)]
        self._events: dict[str, collections.deque] = {}
        self.events_recorded = 0
        for obj in objectives or ():
            self.add_objective(obj)

    @property
    def enabled(self) -> bool:
        return self._enabled

    @property
    def windows_s(self) -> tuple:
        return self._windows

    def add_objective(self, obj: Objective) -> None:
        if obj.kind not in OBJECTIVE_KINDS:
            raise ValueError(f"unknown objective kind {obj.kind!r}; "
                             f"expected one of {OBJECTIVE_KINDS}")
        if not (0.0 < obj.budget <= 1.0):
            raise ValueError(f"objective {obj.name!r} budget must be in "
                             f"(0, 1], got {obj.budget}")
        with self._lock:
            self._objectives[obj.name] = obj
            self._events.setdefault(
                obj.name, collections.deque(maxlen=_MAX_EVENTS))

    def objectives(self) -> list[Objective]:
        with self._lock:
            return list(self._objectives.values())

    @classmethod
    def from_config(cls, config,
                    clock: Callable[[], float] = time.time,
                    ) -> "SloRegistry":
        """The ``slo.*`` config surface → a registry. ``slo.objectives``
        names the active kinds; each kind reads its own budget/threshold
        keys."""
        names = [n.strip() for n in config.get_list("slo.objectives")
                 if n.strip()]
        objs: list[Objective] = []
        for name in names:
            if name not in OBJECTIVE_KINDS:
                raise ValueError(
                    f"slo.objectives entry {name!r} unknown; expected "
                    f"kinds from {OBJECTIVE_KINDS}")
            if name == "latency":
                objs.append(Objective(
                    "latency", "latency",
                    budget=config.get_double("slo.objectives.latency.budget"),
                    threshold_s=config.get_double(
                        "slo.objectives.latency.threshold.seconds"),
                    quantile=config.get_double(
                        "slo.objectives.latency.quantile")))
            elif name == "error":
                objs.append(Objective(
                    "error", "error",
                    budget=config.get_double("slo.objectives.error.budget")))
            elif name == "shed":
                objs.append(Objective(
                    "shed", "shed",
                    budget=config.get_double("slo.objectives.shed.budget")))
            elif name == "staleness":
                objs.append(Objective(
                    "staleness", "staleness",
                    budget=config.get_double(
                        "slo.objectives.staleness.budget"),
                    threshold_s=config.get_double(
                        "slo.objectives.staleness.threshold.seconds")))
            elif name == "heal":
                objs.append(Objective(
                    "heal", "heal",
                    budget=config.get_double("slo.objectives.heal.budget"),
                    threshold_s=config.get_double(
                        "slo.objectives.heal.threshold.seconds")))
        windows = tuple(float(w) for w in
                        config.get_list("slo.burn.windows"))
        return cls(objs, enabled=config.get_boolean("slo.enabled"),
                   windows_s=windows,
                   fast_threshold=config.get_double(
                       "slo.burn.fast.threshold"),
                   slow_threshold=config.get_double(
                       "slo.burn.slow.threshold"),
                   clock=clock)

    # -- recording ---------------------------------------------------------
    def record(self, objective: str, bad: bool) -> None:
        """One classified event for one objective (no-op when disabled
        or the objective is not registered)."""
        if not self._enabled:
            return
        with self._lock:
            events = self._events.get(objective)
            if events is None:
                return
            events.append((self._clock(), bool(bad)))
            self.events_recorded += 1

    def record_request(self, seconds: float, status: int) -> None:
        """Classify one front-door response into every request-kind
        objective: latency counts successful responses over/under the
        threshold, error counts non-(200/202/429) statuses, shed counts
        429s."""
        if not self._enabled:
            return
        now = self._clock()
        ok = status in (200, 202)
        with self._lock:
            for obj in self._objectives.values():
                if obj.kind == "latency":
                    if ok:
                        self._events[obj.name].append(
                            (now, seconds > obj.threshold_s))
                        self.events_recorded += 1
                elif obj.kind == "error":
                    self._events[obj.name].append(
                        (now, status not in (200, 202, 429)))
                    self.events_recorded += 1
                elif obj.kind == "shed":
                    self._events[obj.name].append((now, status == 429))
                    self.events_recorded += 1

    def observe_staleness(self, age_s: float) -> None:
        """Staleness-age objective seam (the facade's stale-serving
        observations): bad when the served age exceeds the threshold."""
        if not self._enabled:
            return
        with self._lock:
            for obj in self._objectives.values():
                if obj.kind == "staleness":
                    self._events[obj.name].append(
                        (self._clock(), age_s > obj.threshold_s))
                    self.events_recorded += 1

    def observe_heal(self, duration_s: float) -> None:
        """Time-to-heal objective seam (fed from cleared heal-ledger
        chains): bad when the heal took longer than the threshold."""
        if not self._enabled:
            return
        with self._lock:
            for obj in self._objectives.values():
                if obj.kind == "heal":
                    self._events[obj.name].append(
                        (self._clock(), duration_s > obj.threshold_s))
                    self.events_recorded += 1

    # -- evaluation --------------------------------------------------------
    def _counts_locked(self, objective: str, now: float,
                       window_s: float) -> tuple[int, int]:
        good = bad = 0
        cutoff = now - window_s
        for t, is_bad in self._events[objective]:
            if t < cutoff:
                continue
            if is_bad:
                bad += 1
            else:
                good += 1
        return good, bad

    def _prune_locked(self, now: float) -> None:
        horizon = now - max(self._windows) - _PRUNE_SLACK_S
        for events in self._events.values():
            while events and events[0][0] < horizon:
                events.popleft()

    def burn_rates(self, objective: str) -> dict[float, float]:
        """window seconds → burn rate (bad_fraction / budget; 0.0 when
        the window holds no events — never NaN)."""
        with self._lock:
            obj = self._objectives.get(objective)
            if obj is None:
                return {}
            now = self._clock()
            self._prune_locked(now)
            out = {}
            for w in self._windows:
                good, bad = self._counts_locked(objective, now, w)
                total = good + bad
                frac = bad / total if total else 0.0
                out[w] = frac / obj.budget
            return out

    def budget_remaining(self, objective: str) -> float:
        """Error budget left over the LONGEST window, clamped [0, 1]."""
        with self._lock:
            obj = self._objectives.get(objective)
            if obj is None:
                return 1.0
            now = self._clock()
            good, bad = self._counts_locked(objective, now,
                                            max(self._windows))
            total = good + bad
            frac = bad / total if total else 0.0
        return min(1.0, max(0.0, 1.0 - frac / obj.budget))

    def burning(self, objective: str) -> bool:
        """The multi-window alert rule: the fast pair (windows 0 and 1)
        both over the fast threshold, OR the slow pair (2 and 3) both
        over the slow threshold."""
        rates = self.burn_rates(objective)
        if not rates:
            return False
        w = self._windows
        fast = rates[w[0]] > self.fast_threshold \
            and rates[w[1]] > self.fast_threshold
        slow = rates[w[2]] > self.slow_threshold \
            and rates[w[3]] > self.slow_threshold
        return fast or slow

    def evaluate(self) -> dict:
        """Evaluate every objective: burn per window, remaining budget,
        burning verdict — mirrored into the
        ``slo_burn_rate{objective,window}`` /
        ``slo_error_budget_remaining{objective}`` gauges. The latency
        objective also reads the live request-latency quantile from the
        sensor registry (`SensorRegistry.quantile` — the hot caller the
        empty/single-bucket pinning exists for)."""
        out: dict[str, dict] = {}
        for obj in self.objectives():
            rates = self.burn_rates(obj.name)
            remaining = self.budget_remaining(obj.name)
            for w, rate in rates.items():
                SENSORS.gauge("slo_burn_rate", rate,
                              labels={"objective": obj.name,
                                      "window": f"{int(w)}s"})
            SENSORS.gauge("slo_error_budget_remaining", remaining,
                          labels={"objective": obj.name})
            entry = {
                "kind": obj.kind,
                "budget": obj.budget,
                "burnRate": {f"{int(w)}s": round(r, 4)
                             for w, r in rates.items()},
                "budgetRemaining": round(remaining, 4),
                "burning": self.burning(obj.name),
            }
            if obj.kind in ("latency", "staleness", "heal"):
                entry["thresholdSeconds"] = obj.threshold_s
            if obj.kind == "latency":
                observed = SENSORS.quantile("serving_request_seconds",
                                            obj.quantile)
                entry["quantile"] = obj.quantile
                entry["observedQuantileS"] = round(observed, 6) \
                    if observed is not None else None
            out[obj.name] = entry
        return out

    def scenario_violations(self, **floors) -> list[str]:
        """The twin's floor verdicts through the registry — one SLO
        definition for production and twin (ScenarioScore delegates to
        the same renderer)."""
        return scenario_floor_violations(**floors)

    def scenario_margins(self, **floors) -> dict:
        """The twin's floor MARGINS through the registry — the red-team
        miner's ranking signal (round 22), kept next to the verdict
        renderer so margin<0 and a rendered verdict can never drift
        apart."""
        return scenario_floor_margins(**floors)

    def state(self) -> dict:
        """The ``GET /slo`` body: config surface + live evaluation."""
        with self._lock:
            counts = {name: len(events)
                      for name, events in self._events.items()}
            recorded = self.events_recorded
        return {
            "sloEnabled": self._enabled,
            "windowsS": [int(w) for w in self._windows],
            "fastBurnThreshold": self.fast_threshold,
            "slowBurnThreshold": self.slow_threshold,
            "eventsRecorded": recorded,
            "eventsHeld": counts,
            "objectives": self.evaluate(),
        }


def scenario_floor_violations(*, unhealed: int,
                              time_to_heal_p95_ticks,
                              heal_ticks_floor: int,
                              ticks_below_balancedness: int,
                              balancedness_min: float,
                              moves_per_simhour: float,
                              moves_floor: float,
                              dead_letters: int) -> list[str]:
    """ScenarioScore's SLO floor verdicts — the twin's half of the
    shared SLO definition. The rendered strings are PINNED: twin
    verdicts must stay byte-identical to the pre-registry
    ``scenario.slo.*`` behavior (tests/test_simulator.py)."""
    out: list[str] = []
    if unhealed:
        out.append(f"unhealed_faults={unhealed}")
    p95 = time_to_heal_p95_ticks
    if p95 is not None and p95 > heal_ticks_floor:
        out.append(f"time_to_heal_p95={p95}>"
                   f"{heal_ticks_floor}_ticks")
    if ticks_below_balancedness:
        out.append(f"balancedness_below_{balancedness_min}_for_"
                   f"{ticks_below_balancedness}_ticks")
    if moves_floor and moves_per_simhour > moves_floor:
        out.append(f"moves_per_simhour={moves_per_simhour:.1f}>"
                   f"{moves_floor}")
    if dead_letters:
        out.append(f"dead_letters={dead_letters}")
    return out


def scenario_floor_margins(*, unhealed: int,
                           time_to_heal_p95_ticks,
                           heal_ticks_floor: int,
                           balancedness_min_observed,
                           balancedness_min: float,
                           moves_per_simhour: float,
                           moves_floor: float,
                           dead_letters: int) -> dict:
    """Normalized headroom per SLO floor — the red-team miner's ranking
    signal (round 22). Contract with ``scenario_floor_violations``:
    ``margin < 0`` for a floor if and only if that floor's verdict
    string renders (same inputs, same floors), so the frontier's
    "worst case" ordering and the serving verdicts can never disagree.
    0 means exactly at the floor; count-style floors (unhealed faults,
    dead letters) have no continuum above the floor, so a clean run
    reports a fixed +1.0 and a dirty one ``-count``. A disabled moves
    floor (0.0) reports +1.0: it cannot be approached, let alone
    crossed."""
    margins: dict[str, float] = {}
    margins["unhealed_faults"] = 1.0 if not unhealed else -float(unhealed)
    p95 = time_to_heal_p95_ticks
    if p95 is None:
        margins["time_to_heal"] = 1.0
    else:
        margins["time_to_heal"] = round(
            (heal_ticks_floor - float(p95)) / float(max(1, heal_ticks_floor)),
            6)
    if balancedness_min_observed is None:
        margins["balancedness"] = 1.0
    else:
        margins["balancedness"] = round(
            (float(balancedness_min_observed) - balancedness_min) / 100.0, 6)
    if moves_floor:
        margins["moves_per_simhour"] = round(
            (moves_floor - moves_per_simhour) / max(moves_floor, 1e-9), 6)
    else:
        margins["moves_per_simhour"] = 1.0
    margins["dead_letters"] = 1.0 if not dead_letters else -float(dead_letters)
    return margins


def scenario_margin(margins: Mapping) -> float:
    """The scalar frontier key: the tightest floor's headroom.
    Negative = at least one floor violated."""
    return min(float(v) for v in margins.values())
