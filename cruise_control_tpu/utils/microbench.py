"""In-process device microbench: per-op-class cost inside a fused
while_loop, at solver-realistic shapes.

The op-class campaign ROADMAP item 2 waits on (scatter/top-k/small-op
marginals on a real chip) lived only in ``tools/microbench_device.py`` —
runnable exclusively from a shell on the host with the TPU grant. This
module is the same measurement as a library call, served by
``GET /kafkacruisecontrol/profile?microbench=true`` so the marginals are
one HTTP call away the day the TPU tunnel unwedges (the CLI tool now
wraps this module, so the two can never drift).

Marginal method per class (tools/profile_round.py discipline): run k and
2k iterations of a tight ``lax.while_loop`` of the class's body and
report ``(t2k - tk) / k`` — dispatch glue and link RTT cancel.
"""

from __future__ import annotations

import time
from functools import partial

# Op classes, in the order they appear in the solver round body's cost
# profile (see tools/profile_parts.py): top-k selections over the
# flattened replica axis, segment reductions for per-broker aggregates,
# grid gathers, scatter applies, elementwise sweeps, and the pairwise
# cumulative-select mask. The last three are the direct-assignment
# transport kernel's op classes (analyzer.direct, round 17): the
# multi-key segmented sort of the replica axis, the cumsum
# rank-assignment (cumulative profile + per-card binary search), and
# the one-shot scatter apply of a full mover batch — so the ROADMAP
# item-2 chip campaign can attribute the new kernel in the same
# ``GET /profile?microbench=true`` call as the greedy round's classes.
# The round-21 sparse plan adds three more: the cell-aggregate segment
# sum onto the [G, B] count plane, the fractional-target systematic
# rounding (hash uniforms + per-group cumsum diff), and the
# stride-interleaved composite-key sort the mesh rank layout pays
# instead of the plain segsort. Round 23 adds the fused variant of that
# sort: quantize the weight into the low bits of ONE composite integer
# key so the interleave costs a single single-key sort frame instead of
# two two-key frames — the candidate replacement the chip campaign
# prices against ``stride_sort``.
CASE_NAMES = ("topk128", "topk1024", "approx1024", "segsum", "segmax",
              "gather_grid", "scatter_m", "elemwise", "pairwise_m",
              "segsort", "rankfill", "scatter_apply",
              "cell_segsum", "frac_round", "stride_sort",
              "stride_sort_fused")


def _build_cases(brokers: int, partitions: int):
    import jax
    import jax.numpy as jnp

    s = 3
    n_flat = partitions * s
    key = jax.random.PRNGKey(0)
    w = jax.random.normal(key, (n_flat,))
    seg = jax.random.randint(key, (n_flat,), 0, brokers)
    grid = 256 * max(16, min(512, brokers // 4))
    gscore = jax.random.normal(key, (grid,))
    gidx = jax.random.randint(key, (grid,), 0, brokers)
    m = 512
    midx = jax.random.randint(key, (m,), 0, brokers)
    mvals = jax.random.normal(key, (m, 4))
    loads = jax.random.normal(key, (brokers, 4))

    def loop(body, carry, iters):
        def c(st):
            return st[0] < iters

        def bd(st):
            i, x = st
            return (i + 1, body(x))
        return jax.lax.while_loop(c, bd, (jnp.int32(0), carry))[1]

    @partial(jax.jit, static_argnames=("iters", "which"))
    def run(x, iters, which):
        if which == "topk128":
            return loop(lambda v: jax.lax.top_k(v + 1.0, 128)[0].sum() + v,
                        x, iters)
        if which == "topk1024":
            return loop(lambda v: jax.lax.top_k(v + 1.0, 1024)[0].sum() + v,
                        x, iters)
        if which == "approx1024":
            return loop(
                lambda v: jax.lax.approx_max_k(v + 1.0, 1024)[0].sum() + v,
                x, iters)
        if which == "segsum":
            return loop(
                lambda v: v + jax.ops.segment_sum(
                    v, seg, num_segments=brokers + 1)[seg] * 1e-9, x, iters)
        if which == "segmax":
            return loop(
                lambda v: v + jax.ops.segment_max(
                    v, seg, num_segments=brokers + 1)[seg] * 1e-9, x, iters)
        if which == "gather_grid":
            return loop(
                lambda v: v + (v[gidx % grid] * 1e-9).sum(), x, iters)
        if which == "scatter_m":
            return loop(
                lambda v: v.at[midx].add(mvals * 1e-9), x, iters)
        if which == "elemwise":
            return loop(lambda v: jnp.where(v > 0, v * 0.999999, v), x, iters)
        if which == "pairwise_m":
            # attach_cumulative-like [m, m] mask + matmul
            def bd(v):
                mask = (v[:, :1] > v[None, :, 0]).astype(jnp.float32)
                return v + (mask @ v) * 1e-9
            return loop(bd, x, iters)
        if which == "segsort":
            # direct.py's mover selection: multi-key (cell, weight) sort
            # of the flattened replica axis + within-run ranks.
            idx = jnp.arange(n_flat, dtype=jnp.int32)

            def bd(v):
                sc, sk, _si = jax.lax.sort((seg.astype(jnp.int32), v, idx),
                                           num_keys=2)
                return v + sk * 1e-9 + (sc[:1] - sc[:1]).astype(v.dtype)
            return loop(bd, x, iters)
        if which == "rankfill":
            # cumsum rank-assignment (fill.deficit_fill_dests shape): a
            # [G, B] cumulative profile + per-card binary search.
            from ..analyzer.fill import deficit_fill_dests
            g_rows = 64
            prof = jnp.abs(jax.random.normal(key, (g_rows, brokers)))
            elig = jnp.ones((brokers,), bool)
            grp = (seg % g_rows).astype(jnp.int32)
            rank = jnp.arange(n_flat, dtype=jnp.int32) % brokers

            def bd(v):
                dst, ok = deficit_fill_dests(grp, rank, prof + v[0] * 1e-9,
                                             prof, elig)
                return v + ok.sum() * 1e-12 + dst.sum() * 1e-12
            return loop(bd, x, iters)
        if which == "cell_segsum":
            # direct.py's count-plane aggregation: segment_sum of the
            # flattened replica axis onto [G, B] cells via the composite
            # cell id grp·(B+1)+broker (the +1 row absorbs unassigned).
            g_rows = 64
            cell = (seg % g_rows) * (brokers + 1) + seg

            def bd(v):
                plane = jax.ops.segment_sum(
                    jnp.ones_like(v), cell,
                    num_segments=g_rows * (brokers + 1))
                return v + plane[cell] * 1e-9
            return loop(bd, x, iters)
        if which == "frac_round":
            # The sparse plan's fractional-target rounding: splitmix
            # hash uniforms per group, then the systematic cumsum-diff
            # rounding over the [G, B] plane (analyzer.direct round 21).
            from ..analyzer.direct import (
                SPARSE_ROUNDING_SEED, _hash_uniform, _round_systematic,
            )
            g_rows = 64
            frac = jnp.abs(jax.random.normal(key, (g_rows, brokers))) * 0.7
            gids = jnp.arange(g_rows, dtype=jnp.int32)

            def bd(v):
                u = _hash_uniform(gids, v[0, 0].astype(jnp.int32),
                                  SPARSE_ROUNDING_SEED)
                t = _round_systematic(frac + v * 1e-9, u)
                return v + t * 1e-9
            return loop(bd, frac, iters)
        if which == "stride_sort":
            # The mesh rank layout's extra cost over plain segsort: the
            # composite (key·stride + block) two-key sort PLUS the
            # second group-ordinal sort frame (analyzer.direct round
            # 21, rank_stride treatment).
            stride = 8
            idx = jnp.arange(n_flat, dtype=jnp.int32)
            blk = idx % stride
            ck = seg.astype(jnp.int32) * stride + blk

            def bd(v):
                cs, cv, ci = jax.lax.sort((ck, v, idx), num_keys=2)
                gb = (cs // stride) * stride + blk[ci]
                gs, _gv, _gi = jax.lax.sort((gb, cv, ci), num_keys=2)
                return v + cv * 1e-9 + (gs[:1] - gs[:1]).astype(v.dtype)
            return loop(bd, x, iters)
        if which == "stride_sort_fused":
            # Fused composite-key variant of stride_sort: the weight is
            # quantized to 11 bits and packed under the (key·stride +
            # block) composite, so ONE single-key sort frame yields the
            # interleaved order — ties inside a quantization bucket
            # break by index, which the solver tolerates (ordering
            # within an epsilon band is already arbitrary).
            stride = 8
            idx = jnp.arange(n_flat, dtype=jnp.int32)
            blk = idx % stride
            ck = seg.astype(jnp.int32) * stride + blk

            def bd(v):
                q = (v * 1024.0).astype(jnp.int32)
                fk = ck * 2048 + (q & 2047)
                fs, fv, _fi = jax.lax.sort((fk, v, idx), num_keys=1)
                return v + fv * 1e-9 + (fs[:1] - fs[:1]).astype(v.dtype)
            return loop(bd, x, iters)
        if which == "scatter_apply":
            # one-shot scatter apply of a full mover batch onto [P, S].
            plane = jnp.zeros((partitions, s), jnp.int32)
            rows = jnp.arange(n_flat, dtype=jnp.int32) // s
            cols = jnp.arange(n_flat, dtype=jnp.int32) % s

            def bd(v):
                sel = v > 0
                r = jnp.where(sel, rows, partitions)
                upd = plane.at[r, cols].set(seg.astype(jnp.int32),
                                            mode="drop")
                return v + upd[0, 0].astype(v.dtype) * 1e-9
            return loop(bd, x, iters)
        raise ValueError(which)

    inputs = {"topk128": w, "topk1024": w, "approx1024": w, "segsum": w,
              "segmax": w, "gather_grid": gscore, "scatter_m": loads,
              "elemwise": w, "pairwise_m": mvals, "segsort": w,
              "rankfill": w, "scatter_apply": w, "cell_segsum": w,
              "frac_round": w, "stride_sort": w, "stride_sort_fused": w}
    return run, inputs


def run_microbench(brokers: int = 1000, partitions: int = 100_000,
                   iters: int = 16,
                   cases: tuple[str, ...] | None = None) -> dict:
    """Measure each op class's marginal ms/iteration inside a fused
    while_loop at (brokers, partitions) scale. Returns
    ``{platform, brokers, partitions, iters, results: {case: ms_per_iter
    | {"error": ...}}}`` — a failed class records its error and the rest
    keep running (the same per-case isolation as the CLI tool)."""
    import jax

    run, inputs = _build_cases(brokers, partitions)
    results: dict[str, float | dict] = {}
    for name in (cases or CASE_NAMES):
        if name not in inputs:
            results[name] = {"error": f"unknown case {name!r}"}
            continue
        x = inputs[name]
        try:
            # Warm EACH timed variant (iters is static: k and 2k are
            # separate compilations a smaller warmup would not cover).
            jax.block_until_ready(run(x, iters, name))
            jax.block_until_ready(run(x, 2 * iters, name))
            t0 = time.monotonic()
            jax.block_until_ready(run(x, iters, name))
            t1 = time.monotonic()
            jax.block_until_ready(run(x, 2 * iters, name))
            t2 = time.monotonic()
            results[name] = round(
                ((t2 - t1) - (t1 - t0)) / iters * 1e3, 4)
        except Exception as e:  # noqa: BLE001 — per-case isolation
            results[name] = {"error": f"{type(e).__name__}: {e}"}
    return {"platform": jax.devices()[0].platform,
            "brokers": int(brokers), "partitions": int(partitions),
            "iters": int(iters), "unit": "ms_per_iter",
            "results": results}
