"""Heal ledger: end-to-end anomaly lifecycle tracking.

ROADMAP item 3 targets second-scale anomaly→proposal latency scored by
the twin's time-to-heal SLO — but until this module, time-to-heal was
only measurable *inside* ``testing/simulator.py``. A production process
could not answer "how long did the last broker-failure heal take, and
where did the time go?". The ledger is that ruler: a bounded,
lock-guarded, injectable-clock journal that assigns every anomaly a
correlation id at detection and records phase transitions across the
whole pipeline —

  detected → (alerted / verdict: fix|check|ignore) → fix_started →
  model_built → solve_dispatched / solve_completed (linking the flight
  recorder's pass ids) → proposal_ready → execution_started →
  per-batch execution_progress → execution_finished → **cleared**
  (the violation re-checked clear), or a terminal alternative:
  ignored / self_cleared / fix_failed_to_start / breaker_skipped /
  dead_lettered / evicted.

Correlation rides the pipeline AMBIENTLY (the ``cluster_label`` /
tracing discipline): the detector manager opens a chain at ``report()``
and enters ``heal_scope(handle)`` around the notifier consult and the
fix dispatch; the facade's model/solve seams, the fleet scheduler's
queue, the megabatch runner, and the executor all record onto
``current_heal()`` with zero plumbing. Handles are BOUND to their
ledger, so a fleet process (one ledger per cluster facade) and an
embedded digital twin (its own facade, its own sim-clocked ledger)
never cross-pollinate — the same isolation rule as
``configure_observability=False``.

Contract (pinned in tests/test_heal_ledger.py, the flight-recorder
family):

- **Observation never changes behavior**: the ledger reads values the
  pipeline already computed — proposals and final assignments are
  byte-identical with the ledger on or off.
- **Near-zero disabled overhead**: disabled, every hook resolves to the
  shared ``NO_HEAL`` no-op handle; bench emits the measured ns/call as
  ``heal_ledger_noop_overhead``.
- **Cross-validated against the twin**: on the injectable clock the
  digital twin drives the ledger and ``ScenarioScore`` from the same
  health observation, so per-fault ledger heal durations equal the
  score's time-to-heal ticks exactly (tests/test_heal_ledger.py).

Served as ``GET /kafkacruisecontrol/heals`` (VIEWER) and exported as
``heal_phase_seconds{phase=}`` / ``time_to_heal_seconds{type=,warm=}``
histograms (``warm`` slices heal latency by warm-path adoption — the
round-18 always-hot campaign's ruler), the ``heals_open{type=}`` gauge,
and the per-type ``self_healing_started_total{type=}`` counter
(detector/manager.py).
"""

from __future__ import annotations

import contextvars
import json
import threading
import time
from contextlib import contextmanager

from .sensors import SENSORS, current_cluster_label

# Heal durations span "one solve" to "hours of escalation": a wider
# log-spaced ladder than the default span buckets.
HEAL_BUCKETS = (0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 150.0, 300.0,
                600.0, 1800.0, 3600.0, 14400.0)

#: Terminal outcomes a chain can resolve with (documented vocabulary —
#: tests pin that every escalation path lands on one of these).
OUTCOMES = ("cleared", "self_cleared", "ignored", "fix_failed_to_start",
            "breaker_skipped", "dead_lettered", "evicted")

#: Anomaly types whose heal is a cluster-health condition: a healthy
#: cluster observation (``observe_health``) closes their open chains,
#: mirroring ScenarioScore's heal-event semantics.
HEALTH_TYPES = ("BROKER_FAILURE", "DISK_FAILURE")


class _NullHealHandle:
    """Shared no-op handle: the disabled path (and every call site with
    no heal in flight) costs one attribute load + one empty-method call
    per record site — all of which sit at phase granularity, never in a
    solver loop."""

    __slots__ = ()
    recording = False

    def phase(self, name: str, **detail) -> None:
        pass

    def resolve(self, outcome: str, **detail) -> None:
        pass


NO_HEAL = _NullHealHandle()

# Ambient correlation (the sensors.cluster_label pattern): the handle of
# the heal currently being worked on this thread/task, or NO_HEAL.
_HEAL: contextvars.ContextVar["HealHandle | _NullHealHandle"] = \
    contextvars.ContextVar("heal_handle", default=NO_HEAL)


def current_heal() -> "HealHandle | _NullHealHandle":
    return _HEAL.get()


@contextmanager
def heal_scope(handle: "HealHandle | _NullHealHandle | None"):
    """Attribute all heal phases recorded inside the block to ``handle``
    (None → NO_HEAL, so call sites need no branching)."""
    token = _HEAL.set(handle if handle is not None else NO_HEAL)
    try:
        yield
    finally:
        _HEAL.reset(token)


class HealChain:
    """One anomaly's lifecycle record (one incident: re-detections of
    the same ongoing condition alias onto the open chain instead of
    opening a new one)."""

    __slots__ = ("chain_id", "anomaly_id", "anomaly_type", "cluster",
                 "signature", "opened_ms", "phases", "outcome",
                 "resolved_ms", "dropped_phases")

    def __init__(self, chain_id: str, anomaly_id: str, anomaly_type: str,
                 cluster: str | None, signature: tuple, opened_ms: int):
        self.chain_id = chain_id
        self.anomaly_id = anomaly_id
        self.anomaly_type = anomaly_type
        self.cluster = cluster
        self.signature = signature
        self.opened_ms = opened_ms
        self.phases: list[dict] = [{"phase": "detected", "atMs": opened_ms,
                                    "durationMs": 0}]
        self.outcome: str | None = None
        self.resolved_ms: int | None = None
        self.dropped_phases = 0

    @property
    def open(self) -> bool:
        return self.outcome is None

    @property
    def last_ms(self) -> int:
        return self.phases[-1]["atMs"] if self.phases else self.opened_ms

    def heal_seconds(self) -> float | None:
        if self.resolved_ms is None:
            return None
        return (self.resolved_ms - self.opened_ms) / 1000.0

    def time_to_start_fix_ms(self) -> int | None:
        for p in self.phases:
            if p["phase"] == "fix_started":
                return p["atMs"] - self.opened_ms
        return None

    def to_dict(self) -> dict:
        out = {
            "chainId": self.chain_id,
            "anomalyId": self.anomaly_id,
            "anomalyType": self.anomaly_type,
            "cluster": self.cluster,
            "signature": list(self.signature),
            "openedAtMs": self.opened_ms,
            "outcome": self.outcome,
            "resolvedAtMs": self.resolved_ms,
            "healSeconds": self.heal_seconds(),
            "timeToStartFixMs": self.time_to_start_fix_ms(),
            "phases": [dict(p) for p in self.phases],
        }
        if self.dropped_phases:
            # No silent caps: a chain past max_phases says how many
            # transitions it could not keep.
            out["droppedPhases"] = self.dropped_phases
        return out


class HealHandle:
    """Correlation handle bound to (ledger, chain): what rides the
    ambient context through the pipeline. Stays valid after the chain
    resolves (late executor phases on a dead-lettered chain are
    recorded; a second resolve is ignored)."""

    __slots__ = ("_ledger", "chain_id")
    recording = True

    def __init__(self, ledger: "HealLedger", chain_id: str):
        self._ledger = ledger
        self.chain_id = chain_id

    def phase(self, name: str, **detail) -> None:
        self._ledger._phase(self.chain_id, name, detail)

    def resolve(self, outcome: str, **detail) -> None:
        self._ledger._resolve(self.chain_id, outcome, detail)


class HealLedger:
    """Bounded, lock-guarded, injectable-clock journal of heal chains.

    One instance per CruiseControl facade (so a fleet's clusters and an
    embedded twin each journal on their OWN clock); the API serves the
    routed facade's ledger. The injectable ``clock`` (seconds; the
    SimClock seam) is the only time source — CCSA004 lists this module
    as deterministic."""

    def __init__(self, enabled: bool = True, max_chains: int = 256,
                 max_phases: int = 64, clock=time.time):
        self._enabled = bool(enabled)
        self._max_chains = max(1, int(max_chains))
        self._max_phases = max(4, int(max_phases))
        self._clock = clock
        self._lock = threading.Lock()
        self._seq = 0
        self._chains: list[HealChain] = []           # oldest first, bounded
        self._by_id: dict[str, HealChain] = {}       # chain_id → chain
        self._aliases: dict[str, str] = {}           # anomaly_id → chain_id
        # Types the heals_open gauge has ever reported: a type whose
        # chains all left the ring must re-emit 0, not freeze at its
        # last nonzero value.
        self._gauge_types: set[str] = set()
        self.chains_opened = 0
        self.chains_resolved = 0

    # -- configuration -----------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._enabled

    def configure(self, enabled: bool | None = None,
                  max_chains: int | None = None,
                  max_phases: int | None = None) -> None:
        with self._lock:
            if enabled is not None:
                self._enabled = bool(enabled)
            if max_chains is not None:
                self._max_chains = max(1, int(max_chains))
            if max_phases is not None:
                self._max_phases = max(4, int(max_phases))

    def _now_ms(self) -> int:
        return int(self._clock() * 1000)

    # -- recording ---------------------------------------------------------
    def open(self, anomaly_type: str, anomaly_id: str,
             signature: tuple = ()) -> "HealHandle | _NullHealHandle":
        """Open a chain at detection (or alias onto the open chain of
        the same ongoing incident: same type + signature ⇒ one chain,
        a ``redetected`` phase, and the new anomaly id resolving to it —
        a detector re-reporting an unfixed condition every interval is
        ONE heal, not many)."""
        if not self._enabled:
            return NO_HEAL
        now = self._now_ms()
        signature = tuple(signature)
        with self._lock:
            for c in reversed(self._chains):
                if c.open and c.anomaly_type == anomaly_type \
                        and c.signature == signature:
                    self._aliases[anomaly_id] = c.chain_id
                    self._append_phase_locked(c, "redetected", now,
                                              {"anomalyId": anomaly_id})
                    return HealHandle(self, c.chain_id)
            self._seq += 1
            chain = HealChain(f"heal-{self._seq}", anomaly_id, anomaly_type,
                              current_cluster_label(), signature, now)
            evicted = None
            if len(self._chains) >= self._max_chains:
                evicted = self._chains.pop(0)
            self._chains.append(chain)
            self._by_id[chain.chain_id] = chain
            self._aliases[anomaly_id] = chain.chain_id
            self.chains_opened += 1
            evicted_open_type = None
            if evicted is not None:
                evicted_open_type = self._drop_locked(evicted)
        SENSORS.count("heal_chains_opened",
                      labels={"type": anomaly_type})
        if evicted_open_type is not None:
            # The ring bound closed a still-open heal: account it like
            # any other terminal so chainsOpened/chainsResolved
            # reconcile and the eviction is visible in /metrics even
            # though the chain itself left the bounded export.
            SENSORS.count("heal_chains_resolved",
                          labels={"type": evicted_open_type,
                                  "outcome": "evicted"})
        self._emit_open_gauges()
        return HealHandle(self, chain.chain_id)

    def handle_for(self, anomaly_id: str) -> "HealHandle | _NullHealHandle":
        """The handle correlated with ``anomaly_id`` (aliases included),
        or NO_HEAL when the ledger is disabled / never saw it."""
        if not self._enabled:
            return NO_HEAL
        with self._lock:
            chain_id = self._aliases.get(anomaly_id)
        return HealHandle(self, chain_id) if chain_id is not None else NO_HEAL

    def _drop_locked(self, chain: HealChain) -> str | None:
        """Forget an evicted chain (ring bound): a still-open chain
        terminates as ``evicted`` and counts as resolved, so
        chains_opened/chains_resolved always reconcile and the eviction
        is observable (the caller emits the outcome sensor outside the
        lock). Returns the anomaly type when an OPEN chain was closed,
        else None. Caller holds the lock."""
        was_open = chain.open
        if was_open:
            chain.outcome = "evicted"
            chain.resolved_ms = self._now_ms()
            self.chains_resolved += 1
        self._by_id.pop(chain.chain_id, None)
        for a in [a for a, cid in self._aliases.items()
                  if cid == chain.chain_id]:
            del self._aliases[a]
        return chain.anomaly_type if was_open else None

    def _append_phase_locked(self, chain: HealChain, name: str, now: int,
                             detail: dict) -> dict | None:
        if len(chain.phases) >= self._max_phases:
            chain.dropped_phases += 1
            return None
        rec = {"phase": name, "atMs": now,
               "durationMs": max(0, now - chain.last_ms)}
        rec.update(detail)
        chain.phases.append(rec)
        return rec

    def _phase(self, chain_id: str, name: str, detail: dict) -> None:
        now = self._now_ms()
        with self._lock:
            chain = self._by_id.get(chain_id)
            if chain is None:
                return
            rec = self._append_phase_locked(chain, name, now, detail)
        if rec is not None:
            SENSORS.observe("heal_phase_seconds", rec["durationMs"] / 1000.0,
                            labels={"phase": name}, buckets=HEAL_BUCKETS)

    def _resolve(self, chain_id: str, outcome: str, detail: dict) -> None:
        now = self._now_ms()
        with self._lock:
            chain = self._by_id.get(chain_id)
            if chain is None or not chain.open:
                return
            if outcome in ("fix_failed_to_start", "breaker_skipped"):
                # SOFT terminals: a re-detection of an incident whose
                # earlier fix IS already in flight (or done) can fail to
                # start a redundant second fix — that must not close the
                # chain out from under the real heal. ``own_fix_started``
                # (popped — bookkeeping, not chain detail) says whether
                # THIS failing attempt recorded a fix_started phase of
                # its own (the dispatch-crash paths do; the no-facade /
                # model-not-ready early-outs do not): the chain
                # terminates only when no OTHER fix ever started; later
                # failed attempts become phases and the chain stays open
                # for cleared/dead_lettered to decide.
                own = 1 if detail.pop("own_fix_started", False) else 0
                attempts = sum(1 for p in chain.phases
                               if p["phase"] == "fix_started")
                if attempts > own:
                    self._append_phase_locked(
                        chain, f"{outcome}_attempt", now, detail)
                    return
            self._append_phase_locked(chain, outcome, now, detail)
            chain.outcome = outcome
            chain.resolved_ms = now
            self.chains_resolved += 1
            a_type = chain.anomaly_type
            dur = chain.heal_seconds()
            # Warm-path adoption slicing (round 18): a chain whose solve
            # was warm-seeded heals on the warm path — the attr the
            # facade stamped on its solve_dispatched phase. Lets
            # time_to_heal_seconds be sliced by warm adoption (the ruler
            # the always-hot campaign is scored against).
            warm = any(p.get("warmStart") for p in chain.phases
                       if p["phase"] == "solve_dispatched")
        SENSORS.count("heal_chains_resolved",
                      labels={"type": a_type, "outcome": outcome})
        if outcome == "cleared":
            SENSORS.observe("time_to_heal_seconds", dur,
                            labels={"type": a_type,
                                    "warm": "true" if warm else "false"},
                            buckets=HEAL_BUCKETS)
        self._emit_open_gauges()

    def _emit_open_gauges(self) -> None:
        counts = self.open_counts()
        with self._lock:
            self._gauge_types |= set(counts)
            types = sorted(self._gauge_types)
        for a_type in types:
            SENSORS.gauge("heals_open", counts.get(a_type, 0),
                          labels={"type": a_type})

    # -- clearing seams ----------------------------------------------------
    def clear_types(self, anomaly_types, via: str = "detector_all_clear",
                    ) -> int:
        """Resolve every open chain of the given types as ``cleared`` —
        the detector all-clear seam: a detector pass that found its
        condition gone IS the violation re-check. Returns the number
        cleared."""
        if not self._enabled:
            return 0
        want = {str(getattr(t, "name", t)) for t in anomaly_types}
        with self._lock:
            due = [c.chain_id for c in self._chains
                   if c.open and c.anomaly_type in want]
        for cid in due:
            self._resolve(cid, "cleared", {"via": via})
        return len(due)

    def observe_health(self, healthy: bool,
                       anomaly_types=HEALTH_TYPES) -> int:
        """Cluster-health observation seam: a healthy observation clears
        the open chains of the cluster-health anomaly types, at the
        observation's clock time. The digital twin calls this where it
        scores per-tick health, so ledger heal durations and
        ``ScenarioScore`` time-to-heal share the same closing anchor;
        a production embedder with its own health probe may do the same
        (the detector all-clear path covers deployments without one, at
        detector-cadence granularity)."""
        if not healthy:
            return 0
        return self.clear_types(anomaly_types, via="health_observation")

    def note_stale(self, staleness_s: float) -> None:
        """Degraded-serving correlation: the facade's stale-proposal
        fallback stamps every open chain, so a heal whose window
        overlapped stale serving carries the evidence. CONSECUTIVE
        stamps coalesce into one phase (updated in place with a
        ``staleServed`` count and the latest staleness) — a dashboard
        polling a broken proposals path must not burn the chain's
        max_phases budget and drop its real lifecycle phases."""
        if not self._enabled:
            return
        now = self._now_ms()
        detail = {"stalenessS": round(float(staleness_s), 3)}
        with self._lock:
            for c in self._chains:
                if not c.open:
                    continue
                last = c.phases[-1]
                if last["phase"] == "stale_serving":
                    last["atMs"] = now
                    last["stalenessS"] = detail["stalenessS"]
                    last["staleServed"] = last.get("staleServed", 1) + 1
                else:
                    self._append_phase_locked(
                        c, "stale_serving", now,
                        {**detail, "staleServed": 1})

    # -- export ------------------------------------------------------------
    def open_counts(self) -> dict[str, int]:
        with self._lock:
            out: dict[str, int] = {}
            for c in self._chains:
                out.setdefault(c.anomaly_type, 0)
                if c.open:
                    out[c.anomaly_type] += 1
            return out

    def open_count(self) -> int:
        with self._lock:
            return sum(1 for c in self._chains if c.open)

    def chains(self, anomaly_type: str | None = None,
               limit: int | None = None) -> list[dict]:
        """Recorded chains, newest first; ``anomaly_type`` filters."""
        with self._lock:
            snapshot = list(self._chains)
        out: list[dict] = []
        if limit is not None and limit <= 0:
            return out
        for c in reversed(snapshot):
            if anomaly_type is not None and c.anomaly_type != anomaly_type:
                continue
            out.append(c.to_dict())
            if limit is not None and len(out) >= limit:
                break
        return out

    def recent_summaries(self, limit: int = 10) -> list[dict]:
        """Compact rows for the STATE detector substate (type, duration,
        outcome — the AnomalyDetectorState recentHeals parity field)."""
        with self._lock:
            snapshot = list(self._chains)[-limit:]
        return [{"chainId": c.chain_id, "type": c.anomaly_type,
                 "outcome": c.outcome,
                 "healSeconds": c.heal_seconds(),
                 "timeToStartFixMs": c.time_to_start_fix_ms()}
                for c in reversed(snapshot)]

    def mean_time_to_start_fix_ms(self) -> float | None:
        """Mean detected→fix_started latency over recorded chains that
        started a fix (AnomalyDetectorState.meanTimeToStartFix parity);
        None when no fix ever started."""
        with self._lock:
            vals = [c.time_to_start_fix_ms() for c in self._chains]
        vals = [v for v in vals if v is not None]
        if not vals:
            return None
        return round(sum(vals) / len(vals), 3)

    def heal_durations_s(self, anomaly_type: str | None = None,
                         ) -> list[float]:
        """Sorted heal durations (seconds) of CLEARED chains — the
        bench/CI heal_p50/p99 hook and the twin cross-validation's
        ground-truth comparison surface."""
        with self._lock:
            vals = [c.heal_seconds() for c in self._chains
                    if c.outcome == "cleared"
                    and (anomaly_type is None
                         or c.anomaly_type == anomaly_type)]
        return sorted(v for v in vals if v is not None)

    def dump_json(self, path: str) -> int:
        """Write every retained chain as one JSON document (bench/CI
        observability artifact). Returns the number of chains written."""
        chains = self.chains()
        doc = {"numChains": len(chains), "chains": chains}
        with open(path, "w") as f:
            json.dump(doc, f, indent=1)
        return len(chains)

    def clear(self) -> None:
        with self._lock:
            self._chains.clear()
            self._by_id.clear()
            self._aliases.clear()
