"""Request-scoped span tracing for the rebalance pipeline.

Dapper-style (Sigelman et al., 2010) span trees over every operation the
service runs: a rebalance cycle becomes one trace — sample fetch →
aggregate → model assembly (cache hit/miss, transfer bytes) → per-goal
solve → proposal diff → execution — instead of forty disconnected
counters. The reference exposes ~40 JMX sensors but nothing that explains
*why* one proposal took 12 s; spans carry the causality.

Design points:

- **Contextvar propagation** (the same pattern as ``sensors.cluster_label``
  and ``progress.OperationProgress``): deep layers open child spans with
  no plumbing; a span opened on a worker thread with no ambient parent
  becomes its own trace root (the fleet scheduler's jobs, the executor's
  run thread, the background sampling loop).
- **Bounded ring** of recent traces, served by ``GET
  /kafkacruisecontrol/trace`` as OTLP-compatible JSON span trees
  (traceId/spanId/parentSpanId/startTimeUnixNano/attributes key-value
  shape), filterable by cluster and operation.
- **Automatic histograms**: every span close records into the
  ``trace_span_seconds`` histogram (one series per span name, ambient
  cluster label applies) so ``/metrics`` grows a ``_bucket`` latency
  distribution per pipeline stage with zero extra call sites.
- **JSONL dump** (``configure(jsonl_path=...)``): bench runs append one
  JSON line per completed trace for offline analysis / CI artifacts.
- **Zero-cost when disabled**: ``span()`` returns a shared no-op context
  manager — no allocation, no contextvar write, no clock read — so the
  config flag removes tracing from the solver hot path entirely.
"""

from __future__ import annotations

import collections
import itertools
import json
import os
import threading
import time

from .sensors import SENSORS, current_cluster_label

import contextvars

_CURRENT: contextvars.ContextVar["Span | None"] = \
    contextvars.ContextVar("trace_current_span", default=None)

# Monotone span-id source; thread-safe in CPython (single bytecode next()).
_IDS = itertools.count(1)

SPAN_HISTOGRAM = "trace_span_seconds"


class Span:
    """One timed, attributed node of a trace tree."""

    __slots__ = ("name", "span_id", "parent", "trace_id", "start_ns",
                 "end_ns", "attributes", "children")

    def __init__(self, name: str, parent: "Span | None"):
        self.name = name
        self.parent = parent
        self.span_id = f"{next(_IDS):016x}"
        self.trace_id = parent.trace_id if parent is not None \
            else f"{next(_IDS):032x}"
        self.start_ns = time.time_ns()
        self.end_ns = 0
        self.attributes: dict = {}
        self.children: list[Span] = []

    def set(self, **attributes) -> None:
        """Attach attributes (goal name, candidate count, transfer bytes…)."""
        self.attributes.update(attributes)

    @property
    def duration_s(self) -> float:
        return max(0.0, (self.end_ns - self.start_ns) / 1e9)

    def to_dict(self) -> dict:
        """OTLP-compatible field shape, nested (children inline — the
        trace endpoint serves trees, not flat span lists)."""
        return {
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "parentSpanId": self.parent.span_id if self.parent else "",
            "name": self.name,
            "startTimeUnixNano": str(self.start_ns),
            "endTimeUnixNano": str(self.end_ns),
            "durationMs": round((self.end_ns - self.start_ns) / 1e6, 3),
            "attributes": [{"key": k, "value": _otlp_value(v)}
                           for k, v in self.attributes.items()],
            "children": [c.to_dict() for c in self.children],
        }


def _otlp_value(v) -> dict:
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}  # OTLP JSON encodes int64 as string
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


class _NullSpan:
    """Shared no-op context manager for disabled tracing: the hot path
    pays one attribute load and one ``is None``-style branch, nothing
    else."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set(self, **attributes) -> None:
        pass


_NULL = _NullSpan()


class _SpanScope:
    """Live span context manager: opens on enter, closes (histogram +
    trace completion) on exit. Exceptions mark the span and propagate."""

    __slots__ = ("_tracer", "_span", "_token")

    def __init__(self, tracer: "Tracer", name: str, attributes: dict):
        self._tracer = tracer
        self._span = Span(name, _CURRENT.get())
        if attributes:
            self._span.attributes.update(attributes)

    def __enter__(self) -> Span:
        self._token = _CURRENT.set(self._span)
        return self._span

    def __exit__(self, exc_type, exc, tb) -> bool:
        _CURRENT.reset(self._token)
        if exc_type is not None:
            self._span.attributes.setdefault("error", exc_type.__name__)
        self._tracer._close(self._span)
        return False


class Trace:
    """A completed span tree plus its routing metadata."""

    __slots__ = ("root", "operation", "operations", "cluster", "span_count")

    def __init__(self, root: Span, cluster: str | None, span_count: int):
        self.root = root
        self.operation = str(root.attributes.get("operation", root.name))
        # EVERY operation attribute in the tree, for filtering: a
        # fleet-routed request's root is the scheduler's "fleet.on_demand"
        # wrapper span with the actual runnable ("rebalance") nested one
        # level down — ?operation=rebalance must still find it.
        ops = {self.operation}
        stack = [root]
        while stack:
            s = stack.pop()
            op = s.attributes.get("operation")
            if op is not None:
                ops.add(str(op))
            stack.extend(s.children)
        self.operations = frozenset(ops)
        self.cluster = cluster
        self.span_count = span_count

    def to_dict(self) -> dict:
        return {
            "traceId": self.root.trace_id,
            "operation": self.operation,
            "operations": sorted(self.operations),
            "cluster": self.cluster,
            "startTimeUnixNano": str(self.root.start_ns),
            "durationMs": round(
                (self.root.end_ns - self.root.start_ns) / 1e6, 3),
            "spanCount": self.span_count,
            "root": self.root.to_dict(),
        }


class Tracer:
    """Process-wide tracer: span factory + bounded trace ring + exports."""

    def __init__(self, max_traces: int = 256):
        self._lock = threading.Lock()
        # JSONL appends serialize on their own lock: a multi-KB trace line
        # is bigger than any atomic-append guarantee, and two threads
        # closing root spans concurrently must not interleave bytes in
        # the dump — but the ring lock must not be held across file I/O.
        self._dump_lock = threading.Lock()
        self._enabled = True
        self._ring: collections.deque[Trace] = \
            collections.deque(maxlen=max_traces)
        self._jsonl_path: str | None = None
        self._jsonl_max_bytes = 0
        self._jsonl_max_files = 1
        self.spans_closed = 0
        self.traces_completed = 0
        self.jsonl_rotations = 0

    # -- configuration -----------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._enabled

    def configure(self, enabled: bool | None = None,
                  max_traces: int | None = None,
                  jsonl_path: str | None = ...,
                  jsonl_max_bytes: int | None = None,
                  jsonl_max_files: int | None = None) -> None:
        """Apply the config surface (tracing.enabled / tracing.max.traces /
        tracing.jsonl.path / tracing.jsonl.max.bytes /
        tracing.jsonl.max.files). ``jsonl_path``: ``...`` = leave
        unchanged, None/"" = off, a path = append one JSON line per
        trace. ``jsonl_max_bytes``: rotate the dump before an append
        would push it past this size (0 = unlimited).
        ``jsonl_max_files``: rotated generations kept — the cascade
        renames ``.1→.2→…→.N`` and drops ``.N`` (default 1, today's
        single-``.1`` behavior)."""
        with self._lock:
            if enabled is not None:
                self._enabled = bool(enabled)
            if max_traces is not None and max_traces != self._ring.maxlen:
                self._ring = collections.deque(self._ring,
                                               maxlen=max(1, max_traces))
            if jsonl_path is not ...:
                self._jsonl_path = jsonl_path or None
            if jsonl_max_bytes is not None:
                self._jsonl_max_bytes = max(0, int(jsonl_max_bytes))
            if jsonl_max_files is not None:
                self._jsonl_max_files = max(1, int(jsonl_max_files))

    # -- recording ---------------------------------------------------------
    def span(self, name: str, **attributes):
        """Open a child span of the ambient span (or a new trace root).
        Returns a context manager yielding the Span (``.set(**attrs)``)."""
        if not self._enabled:
            return _NULL
        return _SpanScope(self, name, attributes)

    def record_span(self, name: str, duration_s: float, **attributes) -> None:
        """Attach an ALREADY-TIMED child span to the ambient span (the
        fused-chain path: per-goal wall-clock is apportioned after one
        device dispatch, so the goals' spans cannot be opened live)."""
        if not self._enabled:
            return
        parent = _CURRENT.get()
        span = Span(name, parent)
        span.end_ns = time.time_ns()
        span.start_ns = span.end_ns - int(duration_s * 1e9)
        span.attributes.update(attributes)
        self._close(span)

    def annotate(self, **attributes) -> None:
        """Attach attributes to the ambient span; no-op outside one (deep
        layers can report cache hits / byte counts without plumbing)."""
        if not self._enabled:
            return
        span = _CURRENT.get()
        if span is not None:
            span.attributes.update(attributes)

    def current_span(self) -> Span | None:
        return _CURRENT.get()

    def _close(self, span: Span) -> None:
        if not span.end_ns:
            span.end_ns = time.time_ns()
        SENSORS.observe(SPAN_HISTOGRAM, span.duration_s,
                        labels={"span": span.name})
        parent = span.parent
        if parent is not None:
            parent.children.append(span)
            with self._lock:
                self.spans_closed += 1
            return
        trace = Trace(span, current_cluster_label(),
                      span_count=_count_spans(span))
        with self._lock:
            self.spans_closed += 1
            self.traces_completed += 1
            self._ring.append(trace)
            path = self._jsonl_path
            max_bytes = self._jsonl_max_bytes
            max_files = self._jsonl_max_files
        if path:
            try:
                line = json.dumps(trace.to_dict()) + "\n"
                with self._dump_lock:
                    self._maybe_rotate_jsonl(path, len(line), max_bytes,
                                             max_files)
                    with open(path, "a") as f:
                        f.write(line)
            except OSError:  # pragma: no cover — dump is best-effort
                pass

    def _maybe_rotate_jsonl(self, path: str, incoming: int,
                            max_bytes: int, max_files: int = 1) -> None:
        """Size-capped rotation (tracing.jsonl.max.bytes): when the next
        append would push the dump past the cap, the generation cascade
        runs — ``.{N-1}→.N`` down to ``path→.1`` — keeping
        ``max_files`` rotated generations (tracing.jsonl.max.files;
        bounded total footprint of ~(max_files+1)× the cap).
        ``jsonl_rotations`` counts per generation MOVED, so a deep
        cascade is visible as more than one rotation. Called under
        ``_dump_lock``. A single line larger than the cap still lands
        (in an otherwise-empty file): dropping traces silently would
        defeat the dump's whole purpose."""
        if max_bytes <= 0:
            return
        try:
            size = os.path.getsize(path)
        except OSError:
            return  # no file yet — nothing to rotate
        if size and size + incoming > max_bytes:
            for gen in range(max(1, max_files), 1, -1):
                older = f"{path}.{gen - 1}"
                if os.path.exists(older):
                    os.replace(older, f"{path}.{gen}")
                    self.jsonl_rotations += 1
            os.replace(path, path + ".1")
            self.jsonl_rotations += 1

    # -- export ------------------------------------------------------------
    def traces(self, cluster: str | None = None,
               operation: str | None = None,
               limit: int | None = None) -> list[dict]:
        """Recent traces, newest first, optionally filtered by the cluster
        label they ran under and/or operation name."""
        with self._lock:
            snapshot = list(self._ring)
        out: list[dict] = []
        if limit is not None and limit <= 0:
            return out
        for t in reversed(snapshot):
            if cluster is not None and t.cluster != cluster:
                continue
            if operation is not None and operation not in t.operations:
                continue
            out.append(t.to_dict())
            if limit is not None and len(out) >= limit:
                break
        return out

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


def _count_spans(span: Span) -> int:
    n = 1
    stack = list(span.children)
    while stack:
        s = stack.pop()
        n += 1
        stack.extend(s.children)
    return n


def span_names(trace_dict: dict) -> list[str]:
    """Flat pre-order span-name list of a ``Trace.to_dict()`` payload
    (test/assertion helper)."""
    out: list[str] = []

    def walk(node: dict) -> None:
        out.append(node["name"])
        for c in node["children"]:
            walk(c)

    walk(trace_dict["root"])
    return out


TRACER = Tracer()
