"""On-demand device profiling behind a single-flight gate.

``GET /kafkacruisecontrol/profile?duration_s=`` wraps
``jax.profiler.trace``: the capture window records whatever the live
process executes — in-flight solves, model refreshes, the fleet pacer's
precomputes — into a Perfetto/TensorBoard trace directory the operator
pulls off the host (or CI uploads as an artifact). This is the live
sibling of the offline marginal tools: span tracing (utils.tracing) says
WHICH stage was slow, the profiler says which op inside the XLA program.

Single-flight discipline: ``jax.profiler`` is process-global state — two
overlapping ``start_trace`` calls corrupt each other — so capture runs
under a non-blocking lock and a concurrent request fails fast with
``ProfilerBusyError`` carrying the remaining window, which the API layer
renders as 503 + Retry-After (the circuit-breaker response shape the
clients already understand).

The microbench surface (``?microbench=true``) shares the gate: op-class
while_loop marginals (utils.microbench) also own the device while they
run, and interleaving them with a trace capture would corrupt both
measurements.
"""

from __future__ import annotations

import logging
import os
import threading
import time

LOG = logging.getLogger(__name__)


class ProfilerBusyError(RuntimeError):
    """A capture or microbench is already running. ``retry_after_s`` is
    the remaining window of the in-flight run (API layer: 503 +
    Retry-After, the breaker-style busy response)."""

    def __init__(self, retry_after_s: float):
        retry_after_s = max(0.5, retry_after_s)
        super().__init__(
            f"device profiler busy; retry in {retry_after_s:.1f}s")
        self.retry_after_s = retry_after_s


class DeviceProfiler:
    """Process-wide profiler front-end (single-flight)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._busy_until = 0.0
        self.captures = 0
        self.microbenches = 0
        # Directory sequence, advanced for every ATTEMPT (not just
        # successes): a retry after a failed capture in the same
        # wall-clock second must not reuse the dead attempt's directory
        # and double-count its leftover files.
        self._dir_seq = 0

    def _acquire(self, window_s: float):
        if not self._lock.acquire(blocking=False):
            raise ProfilerBusyError(self._busy_until - time.monotonic())
        self._busy_until = time.monotonic() + window_s

    def capture(self, duration_s: float, trace_dir: str,
                max_duration_s: float = 60.0) -> dict:
        """Record ``duration_s`` of live device activity into a
        timestamped subdirectory of ``trace_dir``. Returns the trace
        location + captured file listing."""
        duration = min(max(float(duration_s), 0.05), max_duration_s)
        self._acquire(duration)
        try:
            import jax
            # Attempt counter in the name: two captures inside one
            # wall-clock second must not share a directory (the second's
            # file listing would double-count the first's output).
            self._dir_seq += 1
            out_dir = os.path.join(
                trace_dir, time.strftime("trace_%Y%m%d_%H%M%S")
                + f"_{self._dir_seq:03d}")
            os.makedirs(out_dir, exist_ok=True)
            t0 = time.monotonic()
            jax.profiler.start_trace(out_dir)
            try:
                time.sleep(duration)
            finally:
                jax.profiler.stop_trace()
            elapsed = time.monotonic() - t0
            files, total = [], 0
            for root, _dirs, names in os.walk(out_dir):
                for n in names:
                    p = os.path.join(root, n)
                    size = os.path.getsize(p)
                    total += size
                    files.append({"path": os.path.relpath(p, out_dir),
                                  "sizeBytes": size})
            self.captures += 1
            from .sensors import SENSORS
            SENSORS.count("profiling_captures")
            SENSORS.record_timer("profiling_capture", elapsed)
            return {"traceDir": out_dir, "durationS": round(duration, 3),
                    "elapsedS": round(elapsed, 3),
                    "numFiles": len(files), "totalBytes": total,
                    "files": sorted(files, key=lambda f: f["path"])}
        finally:
            self._lock.release()

    def microbench(self, brokers: int, partitions: int,
                   iters: int = 16, budget_s: float = 120.0) -> dict:
        """Run the in-process op-class microbench (utils.microbench)
        under the same single-flight gate. ``budget_s`` only sizes the
        Retry-After a concurrent caller sees — the bench itself runs to
        completion."""
        self._acquire(budget_s)
        try:
            from .microbench import run_microbench
            t0 = time.monotonic()
            out = run_microbench(brokers=brokers, partitions=partitions,
                                 iters=iters)
            self.microbenches += 1
            from .sensors import SENSORS
            SENSORS.count("profiling_microbenches")
            out["elapsedS"] = round(time.monotonic() - t0, 3)
            return out
        finally:
            self._lock.release()


PROFILER = DeviceProfiler()
