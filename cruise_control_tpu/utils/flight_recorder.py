"""Solver flight recorder: per-goal, per-dispatch search telemetry.

The two open perf fronts in ROADMAP (acceptance-density-limited count
goals; the never-run real-TPU op-class campaign) are blocked on
VISIBILITY: the search internals were only reachable through offline
tools (``tools/diag_tr_density.py``), and the megastep's donated
on-device loops (round 10) make host-side introspection scarce by
design. This module is the deliberate readback channel:

- **Per-round ring** (single-device megastep path): the chain move
  drivers optionally carry a small ``[ring, stats]`` f32 buffer through
  the ``lax.while_loop`` and write one stats row per search round —
  applied moves, valid/accepted/positive candidate counts, per-source
  winner rows, and the active goal's violation total (the
  ``diag_tr_density`` attribution made first-class, on device). The
  ring rides the megastep's EXISTING async stats readback: the host
  reads it exactly when it reads the dispatch's scalars, so pipelining
  is untouched.
- **Per-dispatch records** (all paths, sharded included): budget,
  rounds, applied, donation/speculative flags, elapsed wall-clock, and
  the AdaptiveDispatch controller's current budget ``k`` — the
  controller state the staleness contract otherwise hides.
- **Per-goal records**: entry/exit violation + objective, offline
  counts, deficit-sizing decisions (``chain.deficit_sized_config``) and
  the search-grid geometry in force.
- **Bounded pass ring**: completed optimization passes live in a
  bounded deque, served by ``GET /kafkacruisecontrol/solver``
  (``?cluster=``, ``?goal=``, ``?entries=``) and exported as
  ``solver_flight_*`` sensors.

Contract (pinned in tests/test_flight_recorder.py):

- **Trajectory parity**: recording adds REDUCTIONS over tensors the
  round body already computes — never a new selection input — so the
  solver trajectory is byte-identical with recording on or off (the
  same discipline as the megastep's budget invariance).
- **Near-zero disabled overhead**: when disabled, every hook resolves
  to a shared no-op object whose methods are empty (the tracing
  ``_NullSpan`` discipline); bench emits the measured ns/call as
  ``flight_recorder_noop_overhead``.
"""

from __future__ import annotations

import collections
import json
import threading
import time

from .sensors import SENSORS, current_cluster_label

# Columns of the on-device per-round stats row (chain._chain_round_body
# collect=True). ``violation`` is the active goal's broker-violation total
# at round ENTRY (the tensors the row reduces over are the pre-apply
# state): trajectory[N] equals exit-of-round N-1, and the goal's recorded
# exit stats carry the final post-pass value — recomputing violations
# post-apply would double the per-round aux work and break the
# reductions-only parity contract.
STAT_COLUMNS = ("applied", "valid", "accepted", "positive", "winners",
                "violation")
STAT_WIDTH = len(STAT_COLUMNS)

# Acceptance-density histogram bounds: density = accepted moves per round
# / selection width, spanning "one move squeezed out of a 2048-wide grid"
# (~5e-4) to a fully saturated round (1.0).
DENSITY_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0)


def decode_ring(ring, rounds: int) -> list[list[float]]:
    """Unscramble a per-round ring buffer: rows were written at
    ``round % len(ring)``, so with more rounds than slots the OLDEST
    surviving row starts at ``rounds % len(ring)``. Returns the rows in
    round order (oldest first), at most ``len(ring)`` of them."""
    import numpy as np
    a = np.asarray(ring)
    n = a.shape[0]
    if n == 0 or rounds <= 0:
        return []
    if rounds <= n:
        rows = a[:rounds]
    else:
        start = rounds % n
        rows = np.concatenate([a[start:], a[:start]])
    return [[float(x) for x in row] for row in rows]


class _NullGoalFlight:
    """Shared no-op goal hook: the disabled path costs one attribute
    load + one empty-method call per record site (all of which sit at
    dispatch/pass granularity, never per-candidate)."""

    __slots__ = ()
    recording = False
    ring_rounds = 0

    def entry(self, *a, **kw) -> None:
        pass

    def exit(self, *a, **kw) -> None:
        pass

    def sizing(self, *a, **kw) -> None:
        pass

    def grid(self, *a, **kw) -> None:
        pass

    def dispatch(self, *a, **kw) -> None:
        pass


class _NullPassFlight:
    __slots__ = ()
    recording = False

    def __enter__(self):
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def goal(self, name: str) -> _NullGoalFlight:
        return _NULL_GOAL

    def record_goal_infos(self, infos) -> None:
        pass

    def set(self, **kw) -> None:
        pass


_NULL_GOAL = _NullGoalFlight()
_NULL_PASS = _NullPassFlight()

# Public no-op goal hook: chain drivers default their ``flight=`` seam to
# this so every record site is an unconditional call on a do-nothing
# object (no branches on the solver driver paths).
NO_FLIGHT = _NULL_GOAL


class GoalFlight:
    """Recorder handle for one goal's optimization inside a pass."""

    __slots__ = ("name", "viol_before", "viol_after", "obj_before",
                 "obj_after", "offline_before", "offline_after",
                 "grid_sources", "grid_dests", "grid_moves",
                 "selection_width", "sizing_info", "dispatches",
                 "_recorder")

    recording = True

    def __init__(self, name: str, recorder: "FlightRecorder"):
        self.name = name
        self._recorder = recorder
        self.viol_before = self.viol_after = None
        self.obj_before = self.obj_after = None
        self.offline_before = self.offline_after = None
        self.grid_sources = self.grid_dests = self.grid_moves = 0
        self.selection_width = 0
        self.sizing_info: dict | None = None
        self.dispatches: list[dict] = []

    @property
    def ring_rounds(self) -> int:
        return self._recorder.ring_rounds

    def entry(self, violation: float, objective: float = 0.0,
              offline: int = 0) -> None:
        self.viol_before = round(float(violation), 4)
        self.obj_before = float(objective)
        self.offline_before = int(offline)

    def exit(self, violation: float, objective: float = 0.0,
             offline: int = 0) -> None:
        self.viol_after = round(float(violation), 4)
        self.obj_after = float(objective)
        self.offline_after = int(offline)

    def grid(self, num_sources: int, num_dests: int,
             moves_per_round: int) -> None:
        self.grid_sources = int(num_sources)
        self.grid_dests = int(num_dests)
        self.grid_moves = int(moves_per_round)
        # Selection admits at most max(moves, sources) candidates per
        # round — the denominator of acceptance density.
        self.selection_width = max(self.grid_moves, self.grid_sources)

    def sizing(self, entry_violation: float, base_moves: int,
               base_sources: int, sized_moves: int, sized_sources: int,
               cap: int) -> None:
        """One deficit-sizing decision (chain.deficit_sized_config)."""
        self.sizing_info = {
            "entryViolation": round(float(entry_violation), 2),
            "baseMoves": int(base_moves), "baseSources": int(base_sources),
            "sizedMoves": int(sized_moves),
            "sizedSources": int(sized_sources), "cap": int(cap),
            "applied": (sized_moves != base_moves
                        or sized_sources != base_sources)}

    def dispatch(self, kind: str, budget: int, rounds: int, applied: int,
                 donated: bool = False, speculative: bool = False,
                 elapsed_s: float = 0.0, controller_k: int | None = None,
                 ring=None) -> None:
        """One device dispatch's readback. ``ring`` is the on-device
        per-round stats buffer (or None on paths without it: swap phases,
        the sharded kernels, speculative re-runs). Acceptance density is
        only defined for MOVE dispatches on a known grid (the recorded
        ``grid()`` geometry is the move config's — swap kernels run their
        own fixed grid, and the single-dispatch whole-chain paths never
        record one): everything else reports 0.0 and stays out of the
        density histogram. Direct-assignment dispatches
        (``kind="direct"``, analyzer.direct — ``budget`` is the sweep cap
        and ``rounds`` the sweeps run) are deliberately in that
        "everything else": a transport solve has no per-round selection
        grid, so folding its moves-per-sweep into the density histogram
        would masquerade as an off-scale greedy density and corrupt the
        exact distribution the kill-attribution investigation reads."""
        density = (float(applied) / max(1, int(rounds))) \
            / self.selection_width \
            if (kind == "move" and not speculative
                and self.selection_width > 0) else 0.0
        rec = {
            "kind": kind, "budget": int(budget), "rounds": int(rounds),
            "applied": int(applied), "donated": bool(donated),
            "speculative": bool(speculative),
            "elapsedS": round(float(elapsed_s), 4),
            "acceptanceDensity": round(density, 6),
        }
        if controller_k is not None:
            rec["controllerK"] = int(controller_k)
        if ring is not None:
            rows = decode_ring(ring, int(rounds))
            rec["rounds_log"] = [
                {c: (int(v) if c != "violation" else round(v, 2))
                 for c, v in zip(STAT_COLUMNS, row)} for row in rows]
        self.dispatches.append(rec)
        self._recorder._on_dispatch(self, rec)

    # -- export ------------------------------------------------------------
    def kill_attribution(self) -> dict | None:
        """Aggregate candidate-kill attribution over every recorded round
        (the diag_tr_density stages): where the grid's cards went. None
        when no per-round rows were captured.

        Stage semantics (matching the counts the round body can reduce
        on-device): ``killedByPriorVeto`` = valid cards a prior goal's
        acceptance vetoed; ``killedByNonPositive`` = accepted cards with
        no positive improvement; ``killedByPerSourceReduce`` = positive
        cards that lost their source row's winner slot
        (search.reduce_per_source — one winner per source); and
        ``killedByDedupRecheck`` = winner rows dropped by the selection
        stage, which bundles per-partition/broker dedup, the
        moves-per-round cap, and the joint acceptance recheck
        (diag_tr_density's own final 'selected after dedup+recheck'
        stage — the three are one fused kernel and not separable without
        re-running selection)."""
        rows = [r for d in self.dispatches for r in d.get("rounds_log", ())]
        if not rows:
            return None
        valid = sum(r["valid"] for r in rows)
        accepted = sum(r["accepted"] for r in rows)
        positive = sum(r["positive"] for r in rows)
        winners = sum(r["winners"] for r in rows)
        applied = sum(r["applied"] for r in rows)
        return {
            "rounds": len(rows), "validCards": valid,
            "killedByPriorVeto": max(0, valid - accepted),
            "killedByNonPositive": max(0, accepted - positive),
            "killedByPerSourceReduce": max(0, positive - winners),
            "killedByDedupRecheck": max(0, winners - applied),
            "applied": applied,
        }

    def violation_trajectory(self) -> list[float]:
        """Per-round active-goal violation totals at round ENTRY (see
        STAT_COLUMNS — entry[N] = exit[N-1]; the final post-pass value is
        ``violationAfter``), in round order, across every move dispatch
        that carried the ring."""
        return [round(r["violation"], 2) for d in self.dispatches
                for r in d.get("rounds_log", ())]

    def to_dict(self) -> dict:
        moves = sum(d["applied"] for d in self.dispatches
                    if not d["speculative"])
        rounds = sum(d["rounds"] for d in self.dispatches
                     if not d["speculative"])
        # Density over MOVE dispatches only, and only when a grid was
        # recorded: the fused/sharded-unbounded goal summaries have no
        # selection width (a raw moves-per-round would masquerade as a
        # density > 1), and swap kernels run their own fixed grid.
        m_moves = sum(d["applied"] for d in self.dispatches
                      if not d["speculative"] and d["kind"] == "move")
        m_rounds = sum(d["rounds"] for d in self.dispatches
                       if not d["speculative"] and d["kind"] == "move")
        density = (m_moves / m_rounds / self.selection_width) \
            if m_rounds and self.selection_width > 0 else 0.0
        # Solve-mode label: without it, a goal bulk-solved by the direct
        # kernel shows near-zero greedy rounds and a ~0 density, which
        # reads as "the search died instantly" — kill attribution must
        # not be misread as zero-density when the transport simply took
        # the work.
        kinds = {d["kind"] for d in self.dispatches}
        if "direct" in kinds:
            mode = "direct+greedy" if kinds & {"move", "swap", "chain"} \
                else "direct"
        else:
            mode = "greedy"
        out = {
            "goal": self.name,
            "solveMode": mode,
            "violationBefore": self.viol_before,
            "violationAfter": self.viol_after,
            "offlineBefore": self.offline_before,
            "offlineAfter": self.offline_after,
            "rounds": rounds, "movesApplied": moves,
            "dispatchCount": len(self.dispatches),
            "acceptanceDensity": round(density, 6),
            "grid": {"sources": self.grid_sources, "dests": self.grid_dests,
                     "movesPerRound": self.grid_moves,
                     "selectionWidth": self.selection_width},
            "dispatches": self.dispatches,
        }
        if self.sizing_info is not None:
            out["deficitSizing"] = self.sizing_info
        kills = self.kill_attribution()
        if kills is not None:
            out["killAttribution"] = kills
            out["violationTrajectory"] = self.violation_trajectory()
        return out


class PassFlight:
    """Context manager recording one optimization pass. Closing appends
    the pass to the recorder's bounded ring and emits its sensors."""

    recording = True

    def __init__(self, recorder: "FlightRecorder", seq: int,
                 shape: tuple[int, int] | None, cluster: str | None):
        self._recorder = recorder
        self.seq = seq
        self.shape = shape
        self.cluster = cluster
        self.started_ms = int(recorder._clock() * 1000)
        self.attributes: dict = {}
        self.goals: list[GoalFlight] = []
        self._t0 = recorder._monotonic()

    def __enter__(self) -> "PassFlight":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is not None:
            self.attributes.setdefault("error", exc_type.__name__)
        self._recorder._close_pass(
            self, self._recorder._monotonic() - self._t0)
        return False

    def goal(self, name: str) -> GoalFlight:
        g = GoalFlight(name, self._recorder)
        self.goals.append(g)
        return g

    def set(self, **attributes) -> None:
        self.attributes.update(attributes)

    def record_goal_infos(self, infos) -> None:
        """Goal-level summaries for the single-dispatch whole-chain paths
        (fused + sharded-unbounded): no per-dispatch detail exists — the
        whole chain ran in ONE XLA execution — but entry/exit violations
        and round/move counts still land in the flight record."""
        for info in infos:
            g = self.goal(info["goal"])
            if "violation_before" in info:
                g.entry(violation=info["violation_before"],
                        offline=info.get("offline_before", 0))
            g.exit(violation=info["residual_violation"],
                   objective=info.get("objective", 0.0),
                   offline=info.get("offline_remaining", 0))
            g.dispatches.append({
                "kind": "chain", "budget": 0, "rounds": info["rounds"],
                "applied": info["moves_applied"], "donated": False,
                "speculative": False, "elapsedS": 0.0,
                "acceptanceDensity": 0.0})

    def to_dict(self) -> dict:
        return {
            "passSeq": self.seq,
            "cluster": self.cluster,
            "path": self.attributes.get("path"),
            "shape": {"partitions": self.shape[0], "brokers": self.shape[1]}
            if self.shape else None,
            "startedAtMs": self.started_ms,
            "durationS": self.attributes.get("durationS"),
            "attributes": {k: v for k, v in self.attributes.items()
                           if k not in ("durationS", "path")},
            "goals": [g.to_dict() for g in self.goals],
        }


class FlightRecorder:
    """Process-wide recorder: pass factory + bounded pass ring + export
    (the ``utils.tracing.Tracer`` pattern)."""

    def __init__(self, max_passes: int = 64, ring_rounds: int = 128,
                 clock=time.time, monotonic=time.monotonic):
        # Injectable clocks (CCSA004 seam, the SimClock discipline): the
        # recorder's pass timestamps/durations are observability-only —
        # already excluded from scenario score JSON (round 12) — but an
        # injected pair keeps a twin's flight dumps replay-stable too.
        self._clock = clock
        self._monotonic = monotonic
        self._lock = threading.Lock()
        self._enabled = True
        self._ring_rounds = int(ring_rounds)
        self._passes: collections.deque[PassFlight] = \
            collections.deque(maxlen=max_passes)
        self.passes_closed = 0
        self.dispatches_recorded = 0

    # -- configuration -----------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self._enabled

    @property
    def ring_rounds(self) -> int:
        """Length of the on-device per-round stats ring. A TRACE-TIME
        constant: changing it recompiles the recording chain kernels, so
        it is process-config, not per-request."""
        return self._ring_rounds

    def configure(self, enabled: bool | None = None,
                  max_passes: int | None = None,
                  ring_rounds: int | None = None) -> None:
        with self._lock:
            if enabled is not None:
                self._enabled = bool(enabled)
            if max_passes is not None \
                    and max_passes != self._passes.maxlen:
                self._passes = collections.deque(
                    self._passes, maxlen=max(1, max_passes))
            if ring_rounds is not None:
                self._ring_rounds = max(0, int(ring_rounds))

    # -- recording ---------------------------------------------------------
    def pass_scope(self, seq: int = 0,
                   shape: tuple[int, int] | None = None,
                   cluster: str | None = None):
        """Open a pass record (context manager). Disabled → shared no-op
        whose ``goal()`` returns the shared no-op goal hook. ``cluster``
        overrides the ambient cluster label — the megabatch solver opens
        one pass PER CLUSTER in the batch from a single worker thread, so
        ``GET /solver`` keeps answering per cluster."""
        if not self._enabled:
            return _NULL_PASS
        return PassFlight(self, seq, shape,
                          cluster if cluster is not None
                          else current_cluster_label())

    def _on_dispatch(self, goal: GoalFlight, rec: dict) -> None:
        with self._lock:
            self.dispatches_recorded += 1
        # Only move dispatches on a known grid carry a defined density —
        # a swap or gridless sample would skew the exact histogram the
        # density investigation reads.
        if rec["speculative"] or rec["kind"] != "move" \
                or goal.selection_width <= 0:
            return
        SENSORS.observe("solver_acceptance_density",
                        rec["acceptanceDensity"],
                        labels={"goal": goal.name},
                        buckets=DENSITY_BUCKETS)

    def _close_pass(self, p: PassFlight, duration_s: float) -> None:
        p.attributes["durationS"] = round(duration_s, 4)
        with self._lock:
            self.passes_closed += 1
            self._passes.append(p)
        SENSORS.count("solver_flight_passes")
        for g in p.goals:
            kills = g.kill_attribution()
            if kills is None:
                continue
            labels = {"goal": g.name}
            SENSORS.count("solver_flight_rounds", kills["rounds"],
                          labels=labels)
            SENSORS.count("solver_flight_killed_prior_veto",
                          kills["killedByPriorVeto"], labels=labels)
            SENSORS.count("solver_flight_killed_nonpositive",
                          kills["killedByNonPositive"], labels=labels)
            SENSORS.count("solver_flight_killed_source_reduce",
                          kills["killedByPerSourceReduce"], labels=labels)
            SENSORS.count("solver_flight_killed_dedup_recheck",
                          kills["killedByDedupRecheck"], labels=labels)
            if g.viol_after is not None:
                SENSORS.gauge("solver_flight_residual_violation",
                              g.viol_after, labels=labels)

    # -- export ------------------------------------------------------------
    def passes(self, cluster: str | None = None, goal: str | None = None,
               limit: int | None = None) -> list[dict]:
        """Recent completed passes, newest first. ``cluster`` filters by
        the ambient cluster label the pass ran under; ``goal`` keeps only
        passes touching that goal AND trims each pass's goal list to it."""
        with self._lock:
            snapshot = list(self._passes)
        out: list[dict] = []
        if limit is not None and limit <= 0:
            return out
        for p in reversed(snapshot):
            if cluster is not None and p.cluster != cluster:
                continue
            d = p.to_dict()
            if goal is not None:
                d["goals"] = [g for g in d["goals"] if g["goal"] == goal]
                if not d["goals"]:
                    continue
            out.append(d)
            if limit is not None and len(out) >= limit:
                break
        return out

    def marker(self) -> int:
        """Opaque position marker for ``passes_since`` (the simulator's
        per-scenario summary hook)."""
        with self._lock:
            return self.passes_closed

    def passes_since(self, marker: int) -> list[dict]:
        """Passes closed after ``marker`` (oldest first), best-effort: the
        bounded ring may already have evicted the oldest ones."""
        with self._lock:
            new = self.passes_closed - marker
            snapshot = list(self._passes)[-new:] if new > 0 else []
        return [p.to_dict() for p in snapshot]

    def dump_json(self, path: str) -> int:
        """Write every retained pass as one JSON document (bench/CI
        artifact). Returns the number of passes written."""
        with self._lock:
            snapshot = list(self._passes)
        doc = {"numPasses": len(snapshot),
               "passes": [p.to_dict() for p in snapshot]}
        with open(path, "w") as f:
            json.dump(doc, f, indent=1)
        return len(snapshot)

    def clear(self) -> None:
        with self._lock:
            self._passes.clear()


FLIGHT = FlightRecorder()


def summarize_passes(passes: list[dict]) -> dict:
    """Aggregate a pass list into the compact summary the digital-twin
    scenario score embeds (wall-clock-free: only counts and densities, so
    the summary is deterministic for a deterministic trajectory)."""
    dispatches = rounds = moves = 0
    direct_dispatches = direct_moves = 0
    kills = {"killedByPriorVeto": 0, "killedByNonPositive": 0,
             "killedByPerSourceReduce": 0, "killedByDedupRecheck": 0}
    by_goal: dict[str, dict] = {}
    for p in passes:
        for g in p.get("goals", ()):
            real = [d for d in g.get("dispatches", ())
                    if not d.get("speculative")]
            dispatches += len(real)
            direct_dispatches += sum(1 for d in real
                                     if d.get("kind") == "direct")
            direct_moves += sum(d["applied"] for d in real
                                if d.get("kind") == "direct")
            g_rounds = sum(d["rounds"] for d in real)
            g_moves = sum(d["applied"] for d in real)
            rounds += g_rounds
            moves += g_moves
            ka = g.get("killAttribution")
            if ka:
                for k in kills:
                    kills[k] += ka[k]
            slot = by_goal.setdefault(
                g["goal"], {"passes": 0, "rounds": 0, "moves": 0,
                            "lastViolationAfter": None,
                            "violationTrajectory": []})
            slot["passes"] += 1
            slot["rounds"] += g_rounds
            slot["moves"] += g_moves
            if g.get("violationAfter") is not None:
                slot["lastViolationAfter"] = g["violationAfter"]
                # Pass-over-pass exit violations: the scenario-level WHY
                # (a quality drop shows up as a trajectory that stopped
                # descending, not just a worse final number).
                slot["violationTrajectory"].append(g["violationAfter"])
    # Mean density over MOVE dispatches with a recorded grid only (same
    # definition as GoalFlight.to_dict: gridless goal summaries and swap
    # kernels have no defined density).
    width_weighted = [
        (d["applied"], d["rounds"],
         (g.get("grid") or {}).get("selectionWidth", 0))
        for p in passes for g in p.get("goals", ())
        for d in g.get("dispatches", ())
        if not d.get("speculative") and d.get("kind") == "move"]
    width_weighted = [(a, r, w) for a, r, w in width_weighted if w > 0]
    total_rounds = sum(r for _a, r, _w in width_weighted)
    density = (sum(a / w for a, _r, w in width_weighted)
               / total_rounds) if total_rounds else 0.0
    out = {
        "passes": len(passes), "dispatches": dispatches,
        "rounds": rounds, "movesApplied": moves,
        "meanAcceptanceDensity": round(density, 6),
        "killAttribution": kills,
        "byGoal": {k: by_goal[k] for k in sorted(by_goal)},
    }
    if direct_dispatches:
        # Present only when the direct-assignment kernel ran, so the
        # scenario score JSON (byte-identical pinned digests) is
        # untouched on the greedy-only paths.
        out["directDispatches"] = direct_dispatches
        out["directMoves"] = direct_moves
    return out
