"""Typed operation progress (async/progress/OperationProgress.java).

A user task carries an ``OperationProgress``; the facade/monitor/analyzer
record typed steps as the operation advances, and the USER_TASKS endpoint +
the 202 in-flight response surface them mid-flight. The current task's
progress travels via a ``contextvars.ContextVar`` so deep layers (the load
monitor, the optimizer) need no plumbing — the same role as the reference
passing the OperationProgress object down its runnables.
"""

from __future__ import annotations

import contextvars
import threading
import time

_current: contextvars.ContextVar["OperationProgress | None"] = \
    contextvars.ContextVar("operation_progress", default=None)

# Step names mirror the reference's typed steps (OperationProgress.java):
# Pending, RetrievingMetrics, AggregatingMetrics, GeneratingClusterModel,
# OptimizationForGoal, WaitingForClusterModel.


class OperationProgress:
    def __init__(self, operation: str = ""):
        self.operation = operation
        self._lock = threading.Lock()
        self._steps: list[dict] = []

    def start_step(self, description: str) -> None:
        now = time.time()
        with self._lock:
            if self._steps:
                self._steps[-1].setdefault("durationS", round(
                    now - self._steps[-1]["startS"], 3))
                self._steps[-1]["completionPercentage"] = 100.0
            self._steps.append({"step": description, "startS": now,
                                "completionPercentage": 0.0})

    def done(self) -> None:
        with self._lock:
            if self._steps:
                self._steps[-1].setdefault("durationS", round(
                    time.time() - self._steps[-1]["startS"], 3))
                self._steps[-1]["completionPercentage"] = 100.0

    def to_list(self) -> list[dict]:
        with self._lock:
            return [{"step": s["step"],
                     "completionPercentage": s["completionPercentage"],
                     **({"durationS": s["durationS"]} if "durationS" in s
                        else {})}
                    for s in self._steps] or \
                [{"step": "Pending", "completionPercentage": 0.0}]


def set_current(progress: OperationProgress | None):
    return _current.set(progress)


def step(description: str) -> None:
    """Record a step on the ambient operation's progress (no-op outside a
    tracked user task)."""
    progress = _current.get()
    if progress is not None:
        progress.start_step(description)
