"""Typed operation progress (async/progress/OperationProgress.java).

A user task carries an ``OperationProgress``; the facade/monitor/analyzer
record typed steps as the operation advances, and the USER_TASKS endpoint +
the 202 in-flight response surface them mid-flight. The current task's
progress travels via a ``contextvars.ContextVar`` so deep layers (the load
monitor, the optimizer) need no plumbing — the same role as the reference
passing the OperationProgress object down its runnables.
"""

from __future__ import annotations

import contextvars
import threading
import time

_current: contextvars.ContextVar["OperationProgress | None"] = \
    contextvars.ContextVar("operation_progress", default=None)

# Step names mirror the reference's typed steps (OperationProgress.java):
# Pending, RetrievingMetrics, AggregatingMetrics, GeneratingClusterModel,
# OptimizationForGoal, WaitingForClusterModel.


class OperationProgress:
    def __init__(self, operation: str = ""):
        self.operation = operation
        self._lock = threading.Lock()
        self._steps: list[dict] = []

    def _finish_last_locked(self, now: float) -> None:
        """Close the in-flight step exactly once: a re-entered ``done()``
        (layers at different depths both signal completion) must neither
        overwrite the recorded duration nor restart the clock."""
        if not self._steps or self._steps[-1].get("doneFlag"):
            return
        last = self._steps[-1]
        last.setdefault("durationS", round(now - last["startS"], 3))
        last["completionPercentage"] = 100.0
        last["doneFlag"] = True

    def start_step(self, description: str,
                   estimate_s: float | None = None) -> None:
        """Open a new step (closing the previous one). ``estimate_s`` is
        the layer's expected duration, letting ``to_list()`` report a
        LIVE completionPercentage for the in-flight step instead of a
        frozen 0.0 (e.g. the monitor passes its last model-build time)."""
        now = time.time()
        with self._lock:
            self._finish_last_locked(now)
            step = {"step": description, "startS": now,
                    "completionPercentage": 0.0}
            if estimate_s is not None and estimate_s > 0:
                step["estimateS"] = float(estimate_s)
            self._steps.append(step)

    def done(self) -> None:
        with self._lock:
            self._finish_last_locked(time.time())

    def to_list(self) -> list[dict]:
        now = time.time()
        with self._lock:
            out = []
            for s in self._steps:
                pct = s["completionPercentage"]
                if not s.get("doneFlag") and "estimateS" in s:
                    # Live estimate for the in-flight step, clamped below
                    # 100: only done() may declare completion.
                    pct = min(99.0, round(
                        100.0 * (now - s["startS"]) / s["estimateS"], 1))
                out.append({"step": s["step"], "completionPercentage": pct,
                            **({"durationS": s["durationS"]}
                               if "durationS" in s else {})})
            return out or [{"step": "Pending", "completionPercentage": 0.0}]


def set_current(progress: OperationProgress | None):
    return _current.set(progress)


def step(description: str, estimate_s: float | None = None) -> None:
    """Record a step on the ambient operation's progress (no-op outside a
    tracked user task)."""
    progress = _current.get()
    if progress is not None:
        progress.start_step(description, estimate_s=estimate_s)
