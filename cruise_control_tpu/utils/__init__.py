"""Cross-cutting utilities (host-platform control, small helpers)."""

from cruise_control_tpu.utils.platform import force_host_cpu_devices

__all__ = ["force_host_cpu_devices"]
