"""Deterministic fault injection for the whole rebalance pipeline.

The resilience layer (utils/resilience.py) is only trustworthy if it is
*exercised*: this module wraps any admin backend or metric sampler and
injects timeouts, transient errors, partial metadata, slow calls, and
broker flaps on a SEEDED, WALL-CLOCK-FREE schedule. The same seed
replays the same fault sequence byte-for-byte, so the chaos suite
(tests/test_chaos.py) asserts exact convergence with zero flakes and
the tier-1 CPU run stays deterministic.

Fault decisions are a pure function of (seed, op, per-op call index)
via crc32 — no PRNG stream that concurrent threads could reorder. A
"slow" fault never sleeps (that would couple the tier-1 run to real
time); it is accounted in ``injected`` and surfaced as a sensor so
tests can assert the schedule fired without paying for it.

Production hook: ``chaos.enabled=true`` makes the facade wrap its admin
backend here (game-day drills against a staging cluster); the keys are
``chaos.seed`` / ``chaos.fault.rate`` / ``chaos.broker.flap.rate``.
"""

from __future__ import annotations

import threading
import time
import zlib
from collections import Counter

_U32 = float(0xFFFFFFFF)

# Fault kinds a schedule rotates through; broker flaps are separate
# (rate-gated on their own knob — killing destinations mid-move is DEAD-
# task semantics, not a retryable blip, so convergence tests opt in).
FAULT_KINDS = ("timeout", "transient", "partial", "slow")


class ChaosTimeout(TimeoutError):
    """Injected call timeout (retryable by default_retryable)."""

    transient = True


class ChaosTransientError(ConnectionError):
    """Injected transient backend error (retryable)."""

    transient = True


class FaultSchedule:
    """Seeded deterministic fault decisions, one counter per op name.

    ``next_fault(op)`` returns a kind from FAULT_KINDS (or None) for
    the N-th call of ``op``; the decision is crc32-uniform in
    ``fault_rate``. ``stop()`` turns all injection off (the "faults
    stop, run converges" phase of the chaos suite); ``max_faults``
    self-stops after a budget.
    """

    def __init__(self, seed: int = 0, fault_rate: float = 0.1,
                 kinds: tuple[str, ...] = FAULT_KINDS,
                 broker_flap_rate: float = 0.0,
                 max_faults: int | None = None):
        self.seed = seed
        self.fault_rate = fault_rate
        self.kinds = kinds
        self.broker_flap_rate = broker_flap_rate
        self.max_faults = max_faults
        self._lock = threading.Lock()
        self._counts: Counter[str] = Counter()
        self._injected = 0
        self._stopped = False

    def stop(self) -> None:
        with self._lock:
            self._stopped = True

    def resume(self) -> None:
        with self._lock:
            self._stopped = False

    @property
    def faults_injected(self) -> int:
        with self._lock:
            return self._injected

    def _hash01(self, op: str, n: int, salt: str = "") -> float:
        return zlib.crc32(f"{self.seed}:{salt}{op}:{n}".encode()) / _U32

    def next_fault(self, op: str) -> str | None:
        with self._lock:
            n = self._counts[op]
            self._counts[op] += 1
            if self._stopped or not self.kinds:
                return None
            if self.max_faults is not None \
                    and self._injected >= self.max_faults:
                return None
            u = self._hash01(op, n)
            if u >= self.fault_rate:
                return None
            self._injected += 1
            kind = self.kinds[
                zlib.crc32(f"{self.seed}:kind:{op}:{n}".encode())
                % len(self.kinds)]
            return kind

    def next_flap(self, op: str) -> bool:
        """Separate broker-flap stream (its own rate and counter)."""
        with self._lock:
            n = self._counts["flap:" + op]
            self._counts["flap:" + op] += 1
            if self._stopped or self.broker_flap_rate <= 0:
                return False
            return self._hash01(op, n, salt="flap:") \
                < self.broker_flap_rate


class _ChaosBase:
    """Shared injection plumbing for backend/sampler decorators."""

    def __init__(self, inner, schedule: FaultSchedule | None = None,
                 seed: int = 0, fault_rate: float = 0.1,
                 broker_flap_rate: float = 0.0):
        self._inner = inner
        self.schedule = schedule or FaultSchedule(
            seed=seed, fault_rate=fault_rate,
            broker_flap_rate=broker_flap_rate)
        self.injected: Counter[str] = Counter()

    def __getattr__(self, name):
        # Test controls (tick, kill_broker, enable_jbod, ...) and any
        # surface not explicitly faulted pass through untouched.
        return getattr(self._inner, name)

    def _fault(self, op: str) -> str | None:
        """Roll the schedule for ``op``; raise for timeout/transient,
        return "partial"/"slow"/None for the caller to act on."""
        kind = self.schedule.next_fault(op)
        if kind is None:
            return None
        self.injected[f"{op}:{kind}"] += 1
        from ..utils.sensors import SENSORS
        SENSORS.count("chaos_faults_injected",
                      labels={"op": op, "kind": kind})
        if kind == "timeout":
            raise ChaosTimeout(f"injected timeout in {op}")
        if kind == "transient":
            raise ChaosTransientError(f"injected transient error in {op}")
        return kind  # partial / slow: degraded result, caller decides


class ChaosAdminBackend(_ChaosBase):
    """Fault-injecting decorator around any ``AdminBackend``.

    - timeout/transient: the call raises (retryable) without reaching
      the inner backend — no partial state.
    - partial: ``describe_partitions``/``replica_logdirs`` drop a
      deterministic 1-in-8 slice of their result (the shrunk-metadata
      failure mode that silently starved the DiskFailureDetector).
    - slow: accounted, never slept (see module docstring).
    - flap: ``alive_brokers`` transiently omits one deterministic
      broker when ``broker_flap_rate`` > 0.
    """

    @classmethod
    def from_config(cls, inner, config) -> "ChaosAdminBackend":
        return cls(inner, seed=config.get_int("chaos.seed"),
                   fault_rate=config.get_double("chaos.fault.rate"),
                   broker_flap_rate=config.get_double(
                       "chaos.broker.flap.rate"))

    # -- mutating calls: raise-before-delegate ------------------------------
    def alter_partition_reassignments(self, targets) -> None:
        self._fault("admin.alter_partition_reassignments")
        return self._inner.alter_partition_reassignments(targets)

    def cancel_partition_reassignments(self, partitions) -> None:
        self._fault("admin.cancel_partition_reassignments")
        return self._inner.cancel_partition_reassignments(partitions)

    def elect_leaders(self, partitions) -> None:
        self._fault("admin.elect_leaders")
        return self._inner.elect_leaders(partitions)

    def alter_replica_logdirs(self, moves):
        self._fault("admin.alter_replica_logdirs")
        return self._inner.alter_replica_logdirs(moves)

    def alter_broker_configs(self, configs) -> None:
        self._fault("admin.alter_broker_configs")
        return self._inner.alter_broker_configs(configs)

    def alter_topic_configs(self, configs) -> None:
        self._fault("admin.alter_topic_configs")
        return self._inner.alter_topic_configs(configs)

    # -- reads: raise or degrade -------------------------------------------
    def list_reassigning_partitions(self):
        self._fault("admin.list_reassigning_partitions")
        return self._inner.list_reassigning_partitions()

    def describe_partitions(self):
        kind = self._fault("admin.describe_partitions")
        parts = self._inner.describe_partitions()
        if kind == "partial":
            # Deterministic 1-in-8 drop keyed off the sorted order so
            # the same seed shrinks the same slice every run.
            keys = sorted(parts)
            return {k: parts[k] for i, k in enumerate(keys) if i % 8 != 7}
        return parts

    def alive_brokers(self):
        self._fault("admin.alive_brokers")
        alive = self._inner.alive_brokers()
        if alive and self.schedule.next_flap("admin.alive_brokers"):
            flapped = sorted(alive)[
                zlib.crc32(f"{self.schedule.seed}:flapped".encode())
                % len(alive)]
            self.injected["admin.alive_brokers:flap"] += 1
            return {b for b in alive if b != flapped}
        return alive

    def describe_logdirs(self):
        self._fault("admin.describe_logdirs")
        return self._inner.describe_logdirs()

    def replica_logdirs(self, brokers=None):
        kind = self._fault("admin.replica_logdirs")
        dirs = self._inner.replica_logdirs(brokers)
        if kind == "partial":
            keys = sorted(dirs)
            return {k: dirs[k] for i, k in enumerate(keys) if i % 8 != 7}
        return dirs

    def describe_broker_configs(self, brokers):
        self._fault("admin.describe_broker_configs")
        return self._inner.describe_broker_configs(brokers)

    def describe_topic_configs(self, topics):
        self._fault("admin.describe_topic_configs")
        return self._inner.describe_topic_configs(topics)


class ChaosSampler(_ChaosBase):
    """Fault-injecting decorator around any ``MetricSampler``: exercises
    the fetcher's per-sampler tolerance + partial-window acceptance.
    "partial" drops a deterministic half of the returned partition
    samples (a sampler that answered for only part of its bucket)."""

    def get_samples(self, partitions, start_ms, end_ms):
        kind = self._fault("sampler.get_samples")
        res = self._inner.get_samples(partitions, start_ms, end_ms)
        if kind == "partial":
            kept = res.partition_samples[::2]
            dropped = len(res.partition_samples) - len(kept)
            from ..monitor.sampling.sampler import SamplerResult
            return SamplerResult(kept, res.broker_samples,
                                 res.skipped_partitions + dropped)
        return res

    def close(self) -> None:
        self._inner.close()


def run_faulted_executor_cycle(num_partitions: int = 24,
                               brokers: tuple[int, ...] = (0, 1, 2, 3),
                               seed: int = 0, fault_rate: float = 0.2,
                               max_attempts: int = 6,
                               dead_letter_attempts: int = 4,
                               rf: int = 2) -> dict:
    """One full executor cycle against the fault-injecting backend:
    rotate every partition's replica set one broker over and execute
    through a ChaosAdminBackend with retries enabled (zero-sleep
    backoff — deterministic and fast). Shared by tests/test_chaos.py
    and bench.py's ``degraded_cycle_s`` extra.

    Returns {elapsed_s, injected, converged, abandoned, task_counts}.
    """
    from ..analyzer.proposals import ExecutionProposal
    from ..executor.admin import InMemoryAdminBackend, PartitionState
    from ..executor.executor import Executor
    from ..utils.resilience import RetryPolicy

    parts: dict[tuple[str, int], PartitionState] = {}
    for i in range(num_partitions):
        t, p = f"t{i % 3}", i // 3
        reps = tuple(brokers[(i + k) % len(brokers)] for k in range(rf))
        parts[(t, p)] = PartitionState(t, p, reps, reps[0], isr=reps)
    backend = InMemoryAdminBackend(parts.values())
    chaos = ChaosAdminBackend(backend, seed=seed, fault_rate=fault_rate)
    policy = RetryPolicy(max_attempts=max_attempts, base_backoff_s=0.0,
                         max_backoff_s=0.0, jitter_ratio=0.0, seed=seed)
    executor = Executor(chaos, synchronous=True,
                        progress_check_interval_s=0.0,
                        adjuster_enabled=False,
                        retry_policy=policy,
                        dead_letter_attempts=dead_letter_attempts)
    proposals = []
    for (t, p), st in sorted(parts.items()):
        new = tuple(brokers[(brokers.index(b) + 1) % len(brokers)]
                    for b in st.replicas)
        proposals.append(ExecutionProposal(
            topic=t, partition=p, old_leader=st.leader,
            old_replicas=st.replicas, new_replicas=new, new_leader=new[0]))
    # ccsa: ok[CCSA004] reports how long the faulted cycle took on the
    # host (bench degraded_cycle_s) — convergence and the injected fault
    # stream stay purely crc32-driven
    t0 = time.perf_counter()
    executor.execute_proposals(proposals, uuid=f"chaos-{seed}")
    # ccsa: ok[CCSA004] observability-only wall measurement (see t0)
    elapsed = time.perf_counter() - t0
    after = backend.describe_partitions()
    converged = all(
        set(after[(pr.topic, pr.partition)].replicas) == set(pr.new_replicas)
        for pr in proposals)
    counts = executor.execution_state()["taskCounts"]
    abandoned = sum(by_state.get("abandoned", 0)
                    for by_state in counts.values())
    return {"elapsed_s": elapsed, "injected": dict(chaos.injected),
            "faults_injected": chaos.schedule.faults_injected,
            "converged": converged and abandoned == 0,
            "abandoned": abandoned, "task_counts": counts}
