"""Fleet-megabatch twin scenario: two drifting clusters, one bucket, one
batched solver.

Round 12's digital twin drives ONE cluster through the real
monitor→analyzer→executor loop; this module runs TWO of them in lockstep
on one shared ``SimClock``, registered in a real ``FleetRegistry`` whose
coalescing ``FleetScheduler`` drains both clusters' paced precomputes
into ONE megabatched device program per sweep (fleet.megabatch, round
14). Each twin takes a broker loss at a different tick and must
self-heal through the real detector/executor machinery WHILE the fleet
keeps both proposal caches warm through batched solves — the CI scenario
matrix's proof that megabatching and self-healing compose.

Determinism: both simulators run off the shared injected clock, the
scheduler runs off the same clock, and solves are seeded — one seed
yields byte-identical event streams, final assignments, and score JSON
for both twins (same contract as ClusterSimulator)."""

from __future__ import annotations

import dataclasses
import json
import logging
import time
import zlib

from .simulator import (
    ClusterSimulator, DriftSpec, ScenarioEvent, ScenarioSpec, SimClock,
)

LOG = logging.getLogger(__name__)

#: The twin spec: same geometry for both clusters (SHARED bucket — the
#: whole point), diurnal drift, one broker loss each at distinct ticks.
#: The fleet grid keys pin the padded bucket to the simulator's own
#: (128-partition, 8-broker) shape so the chain compiles once.
FLEET_MEGABATCH_SPEC = ScenarioSpec(
    name="fleet_megabatch",
    description="Two drifting clusters sharing one bucket, precomputes "
                "megabatched through one device program; each twin "
                "loses a broker and must self-heal through the real "
                "loop while batched solves keep both caches warm.",
    ticks=60,
    drift=DriftSpec(amplitude=0.4, period_ticks=60),
    config_overrides={
        "fleet.bucket.broker.base": 8,
        "fleet.bucket.partition.base": 128,
        "fleet.bucket.topic.base": 8,
        "fleet.megabatch.enabled": True,
        "fleet.megabatch.width": 4,
        "fleet.precompute.cadence.ms": 60_000,
    })

#: Per-twin broker-loss ticks (off the detection cadence, as in
#: broker_loss_drift, so detection latency is part of time-to-heal).
TWIN_EVENTS = {
    "twin-a": (ScenarioEvent(17, "kill_broker", {"broker": 5}),),
    "twin-b": (ScenarioEvent(29, "kill_broker", {"broker": 4}),),
}


#: The red-team correlated variant (round 22): the SAME tick kills a
#: broker in EVERY fleet member sharing the megabatch bucket — the
#: shared-infrastructure outage (a rack power loss under two tenants)
#: the per-twin staggered losses above never exercise. Both heals and
#: both backfill solves land in the SAME scheduler sweeps.
CASCADE_KILL_TICK = 17


def correlated_cascade_events(kill_tick: int = CASCADE_KILL_TICK,
                              ) -> dict[str, tuple[ScenarioEvent, ...]]:
    """Per-twin event scripts for the correlated cross-fleet cascade:
    distinct victims (each twin's own broker), one shared instant."""
    return {
        "twin-a": (ScenarioEvent(kill_tick, "kill_broker", {"broker": 5}),),
        "twin-b": (ScenarioEvent(kill_tick, "kill_broker", {"broker": 4}),),
    }


def run_fleet_cascade(seed: int = 0, ticks: int | None = None,
                      kill_tick: int = CASCADE_KILL_TICK) -> dict:
    """The correlated multi-cluster cascade, full loop: both twins lose
    a broker at the same tick and must self-heal through the shared
    scheduler while megabatched solves keep both caches warm (the
    round-22 red-team satellite: heals clean, zero dead letters)."""
    return run_fleet_megabatch(
        seed=seed, ticks=ticks, name="fleet_correlated_cascade",
        twin_events=correlated_cascade_events(kill_tick))


def run_fleet_megabatch(seed: int = 0, ticks: int | None = None,
                        twin_events: dict | None = None,
                        name: str = "fleet_megabatch") -> dict:
    """Run the twin scenario; returns the flattened record the CI
    scenario matrix and tests read (per-twin scores, merged SLO list,
    megabatch occupancy proof, crc digest over both final assignments).
    ``twin_events`` swaps the per-twin event scripts (the correlated-
    cascade variant above); default = the staggered TWIN_EVENTS."""
    from ..fleet import FleetRegistry, FleetScheduler

    spec = FLEET_MEGABATCH_SPEC
    if twin_events is None:
        twin_events = TWIN_EVENTS
    if name != "fleet_megabatch":
        spec = dataclasses.replace(spec, name=name)
    if ticks is not None:
        spec = dataclasses.replace(spec, ticks=int(ticks))
    # ccsa: ok[CCSA004] observability-only wall measurement (the record's
    # value column); never enters the event stream or score JSON
    t0 = time.perf_counter()
    clock = SimClock()
    sims: dict[str, ClusterSimulator] = {}
    first = None
    for cid, events in twin_events.items():
        twin_spec = dataclasses.replace(spec, events=events)
        sims[cid] = ClusterSimulator(
            twin_spec, seed=seed, clock=clock,
            optimizer=None if first is None else first.cc.optimizer)
        if first is None:
            first = sims[cid]

    scheduler = FleetScheduler(starvation_bound_s=3600.0, clock=clock)
    registry = FleetRegistry(base_config=first.config,
                             optimizer=first.cc.optimizer,
                             scheduler=scheduler)
    assert registry.megabatch is not None, "twin requires megabatch mode"
    for cid, sim in sims.items():
        registry.register(cid, cc=sim.cc)
    try:
        cids = list(sims)
        for tick in range(spec.ticks):
            for i, cid in enumerate(cids):
                sims[cid].run_tick(tick, advance=(i == 0))
            # The fleet side of the tick: pace every due cluster (both
            # share one cadence, so a due sweep is a whole-bucket fill)
            # and drain the queue — coalesced solves run here.
            scheduler.pace_once()
            scheduler.run_pending()
        mb = registry.megabatch.stats()
        scores = {cid: sims[cid].score for cid in cids}
        finals = {cid: {f"{t}-{p}": sorted(st.replicas)
                        for (t, p), st in sorted(
                            sims[cid].backend.describe_partitions().items())}
                  for cid in cids}
    finally:
        # Deregister WITHOUT shutting the embedder-owned facades down
        # (registry.owns_cc=False for cc= registrations), then stop the
        # (threadless) scheduler.
        registry.shutdown()
        scheduler.shutdown()

    digest = zlib.crc32(json.dumps(finals, sort_keys=True).encode())
    slo = [f"{cid}: {v}" for cid in sims
           for v in scores[cid].slo_violations()]
    if not mb["batchesSolved"] or mb["lastOccupancy"] < 2:
        # The scenario exists to prove batched solves actually happened:
        # a run that silently fell back to solo precomputes must fail
        # the matrix, not pass vacuously.
        slo.append(f"no_megabatch_solves (batches={mb['batchesSolved']}, "
                   f"last_occupancy={mb['lastOccupancy']})")
    heal_p95 = [s.time_to_heal_p95_ticks() for s in scores.values()]
    heal_p95 = [h for h in heal_p95 if h is not None]
    bal = [s.balancedness[-1] for s in scores.values() if s.balancedness]
    return {
        "scenario": name,
        "seed": seed,
        "ticks": spec.ticks,
        "sim_hours": round(sum(s.sim_hours for s in scores.values()), 3),
        "replica_moves": sum(s.replica_moves for s in scores.values()),
        "leader_moves": sum(s.leader_moves for s in scores.values()),
        "bytes_mb_per_simhour": round(
            sum(s.bytes_moved_mb for s in scores.values())
            / max(sum(s.sim_hours for s in scores.values()), 1e-9), 1),
        "moves_per_simhour": round(
            sum(s.moves_per_simhour() for s in scores.values()), 2),
        "time_to_heal_p95_ticks": max(heal_p95) if heal_p95 else None,
        "unhealed_faults": sum(s.unhealed() for s in scores.values()),
        "dead_letters": sum(s.dead_letters for s in scores.values()),
        "stale_served": sum(s.stale_served for s in scores.values()),
        "degraded_ticks": sum(s.degraded_ticks for s in scores.values()),
        "balancedness_final": min(bal) if bal else None,
        "events_applied": sum(s.events_applied for s in scores.values()),
        "faults_injected": sum(s.faults_injected for s in scores.values()),
        "slo_violations": slo,
        "assignment_digest": f"{digest:08x}",
        "megabatch_batches": mb["batchesSolved"],
        "megabatch_clusters_solved": mb["clustersSolved"],
        "megabatch_last_occupancy": mb["lastOccupancy"],
        "megabatch_avg_occupancy": mb["avgOccupancy"],
        "wall_s": round(time.perf_counter() - t0, 3),
    }
