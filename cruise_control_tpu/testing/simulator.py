"""Digital-twin scenario harness: a deterministic, wall-clock-free,
time-stepped cluster simulator driving the REAL rebalance pipeline.

The chaos layer (testing/chaos.py, round 9) injects faults into a single
rebalance cycle; this module grows it into the eval harness ROADMAP item
5 names: simulated time advances in configurable ticks, and per tick the
simulator mutates an ``InMemoryAdminBackend``/sampler pair with scripted
and seeded events — load drift (diurnal ramps, hotspot topics), broker
add/remove/demote, disk failures, topic create/delete/partition-expansion
churn, maintenance windows — while the real monitor → analyzer → executor
→ detector loop runs against it on the injectable clock threaded through
the facade (round 11). No ``time.time()`` anywhere on the simulated path:

- LoadMonitor windows fill via ``run_sampling_once(end_ms=sim time)``.
- Anomaly detection runs via ``AnomalyDetectorManager.run_due(sim time)``
  + ``drain_anomalies()`` — the synchronous, clock-injected replacements
  for the scheduler/handler threads. Fixes are REAL facade operations
  (remove_brokers, fix_offline_replicas, rebalance) executed through the
  real Executor against the simulated backend.
- Seeded stochastic events (topic churn) are a pure function of
  (seed, tick) via crc32, same discipline as chaos.FaultSchedule: two
  runs at one seed replay byte-identical event streams, final
  assignments, and ``ScenarioScore`` JSON.

A ``ScenarioScore`` accumulator tracks quality and stability SLOs —
balancedness trajectory, move churn (moves and bytes moved per simulated
hour), time-to-heal after each injected fault, ticks spent degraded or
serving stale proposals, executor dead-letters, SLO-violation count —
emitted as ``scenario_*`` sensors, a ``scenario.run`` span, and a JSON
report. Surfaces: ``?what_if=<scenario>`` on the PROPOSALS endpoint
(scored trajectory, never executes against the live cluster),
``bench.py --scenarios``, and the CI SCENARIO_MATRIX job-summary table.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import math
import time
import zlib
from typing import Callable, Mapping

LOG = logging.getLogger(__name__)

_U32 = float(0xFFFFFFFF)


def _hash01(*parts) -> float:
    """crc32-uniform [0, 1) from any key parts (PYTHONHASHSEED-stable)."""
    return zlib.crc32(":".join(str(p) for p in parts).encode()) / _U32


class SimClock:
    """Monotonic simulated clock, usable directly as the ``clock``
    callable every resilience/detector seam accepts (seconds), with ms
    helpers for the sampling path. ``sleep`` advances simulated time so
    retry backoffs consume sim time, never wall time."""

    def __init__(self, start_s: float = 0.0):
        self._t = float(start_s)

    def __call__(self) -> float:
        return self._t

    def now_s(self) -> float:
        return self._t

    def now_ms(self) -> int:
        return int(self._t * 1000)

    def advance(self, dt_s: float) -> None:
        self._t += dt_s

    def sleep(self, dt_s: float) -> None:
        self.advance(dt_s)


@dataclasses.dataclass(frozen=True)
class ScenarioEvent:
    """One scripted mutation of the simulated cluster at ``tick``.

    ``kind`` is one of the actions ``ClusterSimulator._apply_event``
    dispatches on; ``params`` its arguments. Events whose kind is in
    ``HEAL_TRIGGERING`` open a time-to-heal measurement."""

    tick: int
    kind: str
    params: Mapping = dataclasses.field(default_factory=dict)

    def as_dict(self) -> dict:
        return {"tick": self.tick, "kind": self.kind,
                "params": {k: self.params[k] for k in sorted(self.params)}}


HEAL_TRIGGERING = ("kill_broker", "kill_logdir")


@dataclasses.dataclass(frozen=True)
class DriftSpec:
    """Load-drift shape: rates scale by
    ``global_factor × (1 + amplitude × sin(2π · (t + phase) / period))``
    — the diurnal ramp — on the simulated clock. ``phase_ticks``
    (round 22) shifts where in the wave the scenario starts: the
    red-team miner's phase perturbation, default 0.0 so every existing
    spec's trajectory is byte-identical."""

    amplitude: float = 0.0
    period_ticks: int = 60
    phase_ticks: float = 0.0


@dataclasses.dataclass(frozen=True)
class ScenarioSpec:
    name: str
    description: str = ""
    num_brokers: int = 6
    num_topics: int = 4
    partitions_per_topic: int = 12
    rf: int = 2
    num_racks: int = 3
    ticks: int = 120
    tick_s: float = 60.0
    events: tuple[ScenarioEvent, ...] = ()
    # Seeded generators: callable(seed, spec) -> list[ScenarioEvent],
    # PURE in (seed, spec) so the expanded stream replays identically.
    generators: tuple[Callable, ...] = ()
    drift: DriftSpec = DriftSpec()
    chaos_fault_rate: float = 0.0
    chaos_broker_flap_rate: float = 0.0
    # Brokers with id < num_brokers // 2 get their capacity scaled by
    # this factor (heterogeneous fleets; 1.0 = homogeneous).
    capacity_skew: float = 1.0
    # Base per-broker disk capacity (MB). The heterogeneity scenario sets
    # this near the per-broker footprint so DiskCapacityGoal must place
    # by headroom across the skewed fleet.
    disk_capacity_mb: float = 1e7
    # Base per-broker network-inbound capacity. The forecast scenario
    # sets this just above the steady per-broker ingest so the diurnal
    # peak pushes the hottest broker over NetworkInboundCapacityGoal's
    # threshold — the forecastable violation predictive rebalancing is
    # scored against.
    nw_in_capacity_mb: float = 1e6
    jbod_dirs: int = 0
    config_overrides: Mapping = dataclasses.field(default_factory=dict)

    def expand_events(self, seed: int) -> list[ScenarioEvent]:
        """Scripted events ∪ every generator's seeded stream, in
        deterministic (tick, kind, params) order."""
        out = list(self.events)
        for gen in self.generators:
            out.extend(gen(seed, self))
        return sorted(out, key=lambda e: (e.tick, e.kind,
                                          json.dumps(e.as_dict(),
                                                     sort_keys=True)))


class DriftingSampler:
    """Deterministic load generator with time-varying drift: stable
    crc32-derived per-partition base rates (PYTHONHASHSEED-stable, the
    CCSA004 rule ``SyntheticSampler`` also follows now) scaled by the
    diurnal ramp, a
    global factor, and per-topic hotspot multipliers — all driven off the
    ``end_ms`` sim timestamp the monitor passes in, never wall time."""

    def __init__(self, seed: int = 0, drift: DriftSpec = DriftSpec(),
                 tick_s: float = 60.0, cpu_per_kb: float = 2e-4):
        self._seed = seed
        self._drift = drift
        self._tick_s = tick_s
        self._cpu_per_kb = cpu_per_kb
        self.global_factor = 1.0
        self.hotspots: dict[str, float] = {}

    def _base(self, topic: str, part: int) -> float:
        return _hash01(self._seed, "load", topic, part)

    def disk_mb(self, topic: str, part: int) -> float:
        """Per-partition disk footprint (MB) — the bytes-moved accounting
        the scorer charges when this partition's replica set changes."""
        return 100.0 + 10_000.0 * self._base(topic, part)

    def _factor(self, topic: str, t_ms: int) -> float:
        f = self.global_factor * self.hotspots.get(topic, 1.0)
        if self._drift.amplitude:
            period_s = max(1.0, self._drift.period_ticks * self._tick_s)
            t_s = t_ms / 1000.0 + self._drift.phase_ticks * self._tick_s
            phase = 2.0 * math.pi * t_s / period_s
            f *= 1.0 + self._drift.amplitude * math.sin(phase)
        return max(f, 0.01)

    def get_samples(self, partitions, start_ms: int, end_ms: int):
        from ..metricdef.kafka_metric_def import CommonMetric as CM
        from ..monitor.sampling.samples import (
            BrokerMetricSample, PartitionMetricSample,
        )
        from ..monitor.sampling.sampler import SamplerResult
        psamples = []
        per_broker: dict[int, float] = {}
        for (topic, part), st in partitions.items():
            if st.leader < 0:
                continue
            h = self._base(topic, part)
            bytes_in = (50.0 + 950.0 * h) * self._factor(topic, end_ms)
            bytes_out = 2.0 * bytes_in
            psamples.append(PartitionMetricSample.make(topic, part, end_ms, {
                CM.CPU_USAGE: self._cpu_per_kb * bytes_in,
                CM.DISK_USAGE: self.disk_mb(topic, part),
                CM.LEADER_BYTES_IN: bytes_in,
                CM.LEADER_BYTES_OUT: bytes_out,
                CM.REPLICATION_BYTES_IN_RATE: bytes_in,
                CM.MESSAGE_IN_RATE: bytes_in / 2,
            }))
            per_broker[st.leader] = per_broker.get(st.leader, 0.0) + bytes_in
        bsamples = [BrokerMetricSample.make(b, end_ms, {
            CM.CPU_USAGE.name: min(1.0, self._cpu_per_kb * v),
            CM.LEADER_BYTES_IN.name: v, CM.LEADER_BYTES_OUT.name: 2 * v,
        }) for b, v in sorted(per_broker.items())]
        return SamplerResult(psamples, bsamples, 0)

    def close(self) -> None:
        pass


@dataclasses.dataclass
class HealEvent:
    kind: str
    injected_tick: int
    healed_tick: int | None = None

    @property
    def ticks_to_heal(self) -> int | None:
        if self.healed_tick is None:
            return None
        return self.healed_tick - self.injected_tick


class ScenarioScore:
    """Quality + stability SLO accumulator for one scenario run. Every
    value is derived from simulated state — nothing wall-clock — so the
    JSON report is byte-identical across runs at one seed."""

    def __init__(self, spec: ScenarioSpec, seed: int, config):
        self.spec = spec
        self.seed = seed
        self._slo_bal_min = config.get_double("scenario.slo.balancedness.min")
        self._slo_heal_ticks = config.get_int("scenario.slo.heal.ticks")
        self._slo_moves_hr = config.get_double(
            "scenario.slo.moves.per.simhour")
        self.ticks_run = 0
        self.balancedness: list[float] = []
        self.balancedness_scored_from: int | None = None
        self.ticks_below_balancedness_slo = 0
        self.replica_moves = 0
        self.leader_moves = 0
        self.bytes_moved_mb = 0.0
        self.heal_events: list[HealEvent] = []
        self.stale_served = 0
        self.probe_failures = 0
        self.degraded_ticks = 0
        self.staleness_ticks_max = 0
        self.dead_letters = 0
        self.fixes_started = 0
        self.anomalies_handled = 0
        self.events_applied = 0
        self.faults_injected = 0
        # Flight-recorder summary of every optimizer pass the scenario
        # drove (utils.flight_recorder.summarize_passes): acceptance
        # density, kill attribution, per-goal rounds/moves — the WHY
        # behind a balancedness move, not just that it moved. None when
        # the recorder is disabled. Wall-clock-free, so it keeps the
        # byte-identical-JSON determinism contract.
        self.solver_flight: dict | None = None

    # -- per-tick observation ----------------------------------------------
    def observe_tick(self, tick: int, balancedness: float | None,
                     replica_moves: int, leader_moves: int,
                     bytes_moved_mb: float, healthy: bool,
                     degraded: bool) -> None:
        self.ticks_run = tick + 1
        if balancedness is not None:
            if self.balancedness_scored_from is None:
                self.balancedness_scored_from = tick
            self.balancedness.append(round(balancedness, 3))
            if balancedness < self._slo_bal_min:
                self.ticks_below_balancedness_slo += 1
        self.replica_moves += replica_moves
        self.leader_moves += leader_moves
        self.bytes_moved_mb += bytes_moved_mb
        if degraded:
            self.degraded_ticks += 1
        if healthy:
            for h in self.heal_events:
                if h.healed_tick is None:
                    h.healed_tick = tick

    def open_heal(self, kind: str, tick: int) -> None:
        self.heal_events.append(HealEvent(kind, tick))

    # -- aggregates ---------------------------------------------------------
    @property
    def sim_hours(self) -> float:
        return self.ticks_run * self.spec.tick_s / 3600.0

    def _heal_ticks(self) -> list[int]:
        return sorted(h.ticks_to_heal for h in self.heal_events
                      if h.ticks_to_heal is not None)

    def time_to_heal_p95_ticks(self) -> int | None:
        done = self._heal_ticks()
        if not done:
            return None
        return done[min(len(done) - 1, int(math.ceil(0.95 * len(done))) - 1)]

    def unhealed(self) -> int:
        return sum(1 for h in self.heal_events if h.healed_tick is None)

    def moves_per_simhour(self) -> float:
        return self.replica_moves / max(self.sim_hours, 1e-9)

    def slo_violations(self) -> list[str]:
        # ONE SLO definition for twin and production: the floor verdicts
        # render through utils.slo so GET /slo and the scenario report
        # can never drift apart (strings pinned byte-identical).
        from ..utils.slo import scenario_floor_violations
        return scenario_floor_violations(
            unhealed=self.unhealed(),
            time_to_heal_p95_ticks=self.time_to_heal_p95_ticks(),
            heal_ticks_floor=self._slo_heal_ticks,
            ticks_below_balancedness=self.ticks_below_balancedness_slo,
            balancedness_min=self._slo_bal_min,
            moves_per_simhour=self.moves_per_simhour(),
            moves_floor=self._slo_moves_hr,
            dead_letters=self.dead_letters)

    def slo_margins(self) -> dict:
        # The red-team miner's ranking signal (round 22): normalized
        # per-floor headroom, rendered through the same utils.slo module
        # as the verdicts so margin<0 and a rendered violation can never
        # disagree on one run.
        from ..utils.slo import scenario_floor_margins
        return scenario_floor_margins(
            unhealed=self.unhealed(),
            time_to_heal_p95_ticks=self.time_to_heal_p95_ticks(),
            heal_ticks_floor=self._slo_heal_ticks,
            balancedness_min_observed=(min(self.balancedness)
                                       if self.balancedness else None),
            balancedness_min=self._slo_bal_min,
            moves_per_simhour=self.moves_per_simhour(),
            moves_floor=self._slo_moves_hr,
            dead_letters=self.dead_letters)

    def as_dict(self) -> dict:
        p95 = self.time_to_heal_p95_ticks()
        return {
            "scenario": self.spec.name,
            "seed": self.seed,
            "ticks": self.ticks_run,
            "tick_s": self.spec.tick_s,
            "simHours": round(self.sim_hours, 3),
            "balancedness": {
                "scoredFromTick": self.balancedness_scored_from,
                "final": self.balancedness[-1] if self.balancedness else None,
                "min": min(self.balancedness) if self.balancedness else None,
                "trajectory": self.balancedness,
            },
            "churn": {
                "replicaMoves": self.replica_moves,
                "leaderMoves": self.leader_moves,
                "bytesMovedMb": round(self.bytes_moved_mb, 1),
                "movesPerSimHour": round(self.moves_per_simhour(), 2),
                "bytesMbPerSimHour": round(
                    self.bytes_moved_mb / max(self.sim_hours, 1e-9), 1),
            },
            "heal": {
                "events": [{"kind": h.kind, "injectedTick": h.injected_tick,
                            "healedTick": h.healed_tick,
                            "ticksToHeal": h.ticks_to_heal}
                           for h in self.heal_events],
                "p95Ticks": p95,
                "unhealed": self.unhealed(),
            },
            "degraded": {
                "staleServed": self.stale_served,
                "probeFailures": self.probe_failures,
                "degradedTicks": self.degraded_ticks,
                "stalenessTicksMax": self.staleness_ticks_max,
            },
            "deadLetters": self.dead_letters,
            "solverFlight": self.solver_flight,
            "fixesStarted": self.fixes_started,
            "anomaliesHandled": self.anomalies_handled,
            "eventsApplied": self.events_applied,
            "faultsInjected": self.faults_injected,
            "sloViolations": self.slo_violations(),
        }

    def to_json(self) -> str:
        return json.dumps(self.as_dict(), sort_keys=True)

    def emit_sensors(self) -> None:
        from ..utils.sensors import SENSORS
        labels = {"scenario": self.spec.name}
        SENSORS.count("scenario_runs", labels=labels)
        SENSORS.count("scenario_replica_moves", self.replica_moves,
                      labels=labels)
        SENSORS.count("scenario_slo_violations",
                      len(self.slo_violations()), labels=labels)
        SENSORS.count("scenario_dead_letters", self.dead_letters,
                      labels=labels)
        SENSORS.gauge("scenario_bytes_moved_mb_per_simhour",
                      self.bytes_moved_mb / max(self.sim_hours, 1e-9),
                      labels=labels)
        p95 = self.time_to_heal_p95_ticks()
        if p95 is not None:
            SENSORS.gauge("scenario_time_to_heal_p95_ticks", p95,
                          labels=labels)
        if self.balancedness:
            SENSORS.gauge("scenario_balancedness_final",
                          self.balancedness[-1], labels=labels)


@dataclasses.dataclass
class ScenarioResult:
    spec: ScenarioSpec
    seed: int
    score: ScenarioScore
    events: list[dict]
    final_assignment: dict[str, list[int]]
    wall_s: float

    @property
    def assignment_digest(self) -> str:
        return f"{zlib.crc32(json.dumps(self.final_assignment, sort_keys=True).encode()):08x}"

    def report(self) -> dict:
        return {"score": self.score.as_dict(),
                "events": self.events,
                "finalAssignmentDigest": self.assignment_digest,
                "finalAssignment": self.final_assignment}

    def report_json(self) -> str:
        return json.dumps(self.report(), sort_keys=True)


class ClusterSimulator:
    """Wires a CruiseControl facade to a simulated backend/sampler pair on
    an injected clock and advances the whole loop tick by tick. The
    pipeline objects are the production classes, not doubles: fixes run
    the real optimizer and the real executor task lifecycle against the
    in-memory cluster."""

    def __init__(self, spec: ScenarioSpec, seed: int = 0,
                 config_overrides: Mapping | None = None,
                 optimizer=None, clock: "SimClock | None" = None):
        """``optimizer``/``clock`` are the FLEET-TWIN seams (round 14):
        two simulators sharing one GoalOptimizer and one SimClock model
        two clusters served by one fleet solver — the megabatch twin
        scenario drives them in lockstep (the second twin ticks with
        ``advance=False`` so the shared clock advances once per tick)."""
        from ..common.resources import Resource
        from ..config.cruise_control_config import CruiseControlConfig
        from ..executor.admin import InMemoryAdminBackend, PartitionState
        from ..executor.executor import Executor
        from ..facade import CruiseControl
        from ..monitor.capacity import StaticCapacityResolver
        from ..monitor.load_monitor import LoadMonitor
        from ..utils.resilience import RetryPolicy

        # Config is the source of truth for the tick geometry: the spec
        # feeds the defaults, and ``scenario.tick.seconds`` /
        # ``scenario.default.ticks`` overrides (spec-level or caller-level)
        # re-time the replay — resolved BEFORE the config map is built so
        # the sampling-window geometry below always matches the tick.
        overrides = {**dict(spec.config_overrides),
                     **dict(config_overrides or {})}
        spec = dataclasses.replace(
            spec,
            tick_s=float(overrides.get("scenario.tick.seconds",
                                       spec.tick_s)),
            ticks=int(overrides.get("scenario.default.ticks", spec.ticks)))
        self.spec = spec
        self.seed = seed
        self.clock = clock if clock is not None else SimClock()
        tick_ms = int(spec.tick_s * 1000)
        _g = "cruise_control_tpu.analyzer.goals"
        cfg_map = {
            "scenario.tick.seconds": spec.tick_s,
            "scenario.default.ticks": spec.ticks,
            # Sampling/window geometry: one window per tick so the monitor
            # refreshes the model generation every simulated step.
            "metric.sampling.interval.ms": tick_ms,
            "partition.metrics.window.ms": tick_ms,
            "num.partition.metrics.windows": 4,
            "min.valid.partition.ratio": 0.0,
            # Self-healing on (maintenance plans included), with
            # escalation thresholds in tick units so broker failures heal
            # within the scenario horizon.
            "self.healing.enabled": True,
            "self.healing.maintenance.event.enabled": True,
            "anomaly.detection.interval.ms": 10 * tick_ms,
            "broker.failure.alert.threshold.ms": 0,
            "broker.failure.self.healing.threshold.ms": tick_ms,
            # One padded solver shape for every scenario: topic churn and
            # broker loss stay inside a single (128-partition, 32-broker)
            # bucket, so the chain compiles ONCE across the whole library
            # instead of once per churn step.
            "solver.partition.bucket.size": 128,
            # A short, churn-sensitive goal chain keeps per-tick solves
            # cheap and compiled shapes shared across every scenario.
            "goals": [f"{_g}.RackAwareGoal", f"{_g}.ReplicaCapacityGoal",
                      f"{_g}.DiskCapacityGoal",
                      f"{_g}.ReplicaDistributionGoal"],
            "hard.goals": [f"{_g}.RackAwareGoal",
                           f"{_g}.ReplicaCapacityGoal"],
            "anomaly.detection.goals": [f"{_g}.RackAwareGoal",
                                        f"{_g}.ReplicaDistributionGoal"],
            "max.solver.rounds": 40,
            "failed.brokers.file.path": "",
            # Deterministic, sim-time-only retries.
            "resilience.retry.base.backoff.ms": 0,
            "resilience.retry.max.backoff.ms": 0,
            "resilience.retry.max.attempts": 8,
            "resilience.retry.seed": seed,
            **overrides,
        }
        self.config = CruiseControlConfig(cfg_map)
        self._probe_every = self.config.get_int(
            "scenario.proposal.probe.ticks")

        parts = {}
        for t in range(spec.num_topics):
            for p in range(spec.partitions_per_topic):
                reps = tuple((t + p + k) % spec.num_brokers
                             for k in range(min(spec.rf, spec.num_brokers)))
                parts[(f"t{t}", p)] = PartitionState(
                    f"t{t}", p, reps, reps[0], isr=reps)
        self.backend = InMemoryAdminBackend(parts.values())
        if spec.jbod_dirs:
            self.backend.enable_jbod(
                {b: [f"/d{i}" for i in range(spec.jbod_dirs)]
                 for b in range(spec.num_brokers)})
        admin = self.backend
        self.chaos = None
        self.sampler = DriftingSampler(seed=seed, drift=spec.drift,
                                       tick_s=spec.tick_s)
        sampler = self.sampler
        if spec.chaos_fault_rate > 0 or spec.chaos_broker_flap_rate > 0:
            from .chaos import ChaosAdminBackend, ChaosSampler
            admin = ChaosAdminBackend(
                self.backend, seed=seed, fault_rate=spec.chaos_fault_rate,
                broker_flap_rate=spec.chaos_broker_flap_rate)
            self.chaos = admin
            sampler = ChaosSampler(self.sampler, schedule=admin.schedule)

        base_cap = {Resource.CPU: 100.0, Resource.DISK: spec.disk_capacity_mb,
                    Resource.NW_IN: spec.nw_in_capacity_mb,
                    Resource.NW_OUT: 1e6}
        by_broker = {}
        if spec.capacity_skew != 1.0:
            by_broker = {b: {r: v * spec.capacity_skew
                             for r, v in base_cap.items()}
                         for b in range(spec.num_brokers // 2)}
        caps = StaticCapacityResolver(by_broker, base_cap)
        racks = {b: f"az{b % spec.num_racks}"
                 for b in range(spec.num_brokers)}
        monitor = LoadMonitor(self.config, admin, samplers=[sampler],
                              capacity_resolver=caps, broker_racks=racks)
        executor = Executor(
            admin, synchronous=True, progress_check_interval_s=0.0,
            adjuster_enabled=False,
            retry_policy=RetryPolicy(max_attempts=8, base_backoff_s=0.0,
                                     max_backoff_s=0.0, jitter_ratio=0.0,
                                     seed=seed),
            dead_letter_attempts=6)
        # configure_observability=False: the twin records spans/sensors
        # into the HOST's tracer as-configured — a ?what_if= replay must
        # never rewrite the serving process's tracing settings.
        self.cc = CruiseControl(self.config, admin, load_monitor=monitor,
                                executor=executor, clock=self.clock,
                                optimizer=optimizer,
                                configure_observability=False)
        self._events_by_tick: dict[int, list[ScenarioEvent]] = {}
        self.events = spec.expand_events(seed)
        for e in self.events:
            self._events_by_tick.setdefault(e.tick, []).append(e)
        self.score = ScenarioScore(spec, seed, self.config)
        self._prev_assignment: dict | None = None
        self._last_good_probe_tick = 0

    # -- event application --------------------------------------------------
    def _apply_event(self, e: ScenarioEvent, tick: int) -> None:
        from ..detector.anomaly import MaintenanceEvent, MaintenanceEventType
        p = dict(e.params)
        b = self.backend
        if e.kind == "kill_broker":
            b.kill_broker(int(p["broker"]))
        elif e.kind == "revive_broker":
            b.revive_broker(int(p["broker"]))
        elif e.kind == "kill_logdir":
            b.kill_logdir(int(p["broker"]), p["logdir"])
        elif e.kind == "remove_disks":
            # Operator drain of a failing disk: the real REMOVE_DISKS
            # flow (intra-broker executor phase) against the twin.
            self.cc.remove_disks({int(p["broker"]): [p["logdir"]]},
                                 dryrun=False, reason="scenario drain")
        elif e.kind == "create_topic":
            b.create_topic(p["topic"], int(p["partitions"]),
                           rf=int(p.get("rf", self.spec.rf)))
        elif e.kind == "delete_topic":
            b.delete_topic(p["topic"])
        elif e.kind == "expand_partitions":
            b.expand_partitions(p["topic"], int(p["to"]))
        elif e.kind == "maintenance":
            self.cc.maintenance_reader.submit(MaintenanceEvent(
                event_type=MaintenanceEventType(p["plan"]),
                broker_ids=list(p.get("brokers", ())),
                topics_by_rf={int(k): list(v) for k, v in
                              p.get("topics_by_rf", {}).items()},
                detection_time_ms=self.clock.now_ms()))
        elif e.kind == "set_load":
            self.sampler.global_factor = float(p["factor"])
        elif e.kind == "hotspot":
            self.sampler.hotspots[p["topic"]] = float(p["factor"])
        elif e.kind == "clear_hotspot":
            self.sampler.hotspots.pop(p["topic"], None)
        elif e.kind == "stop_faults":
            if self.chaos is not None:
                self.chaos.schedule.stop()
        elif e.kind == "resume_faults":
            if self.chaos is not None:
                self.chaos.schedule.resume()
        else:
            raise ValueError(f"unknown scenario event kind {e.kind!r}")
        if e.kind in HEAL_TRIGGERING:
            self.score.open_heal(e.kind, tick)
        self.score.events_applied += 1

    # -- health + churn observation -----------------------------------------
    def _snapshot(self) -> dict[tuple[str, int], tuple]:
        # Raw (unwrapped) backend: scoring reads must not roll the fault
        # schedule or see injected partial metadata.
        return {k: (tuple(st.replicas), st.leader)
                for k, st in self.backend.describe_partitions().items()}

    def _healthy(self) -> bool:
        alive = self.backend.alive_brokers()
        for (t, pp), st in self.backend.describe_partitions().items():
            if any(br not in alive for br in st.replicas):
                return False
        dirs = self.backend.describe_logdirs()
        if dirs:
            for (t, pp, br), d in self.backend.replica_logdirs().items():
                if not dirs.get(br, {}).get(d, True):
                    return False
        return True

    def _observe_churn(self, cur: dict) -> tuple[int, int, float]:
        prev = self._prev_assignment
        self._prev_assignment = cur
        if prev is None:
            return 0, 0, 0.0
        replica_moves = leader_moves = 0
        bytes_mb = 0.0
        for key, (reps, leader) in cur.items():
            old = prev.get(key)
            if old is None:
                continue
            if set(old[0]) != set(reps):
                replica_moves += 1
                bytes_mb += self.sampler.disk_mb(*key)
            elif old[1] != leader:
                leader_moves += 1
        return replica_moves, leader_moves, bytes_mb

    def _probe_proposals(self, tick: int) -> bool:
        """Client-style proposals() probe: exercises (and scores) the
        degraded-serving path. Returns True when this tick served
        degraded (stale or failed)."""
        try:
            res = self.cc.proposals()
        except Exception:  # noqa: BLE001 — scored, not fatal
            self.score.probe_failures += 1
            return True
        if res.extra.get("stale"):
            self.score.stale_served += 1
            self.score.staleness_ticks_max = max(
                self.score.staleness_ticks_max,
                tick - self._last_good_probe_tick)
            return True
        self._last_good_probe_tick = tick
        return False

    # -- the loop -----------------------------------------------------------
    def run_tick(self, tick: int, advance: bool = True) -> None:
        mgr = self.cc.anomaly_detector
        if advance:
            self.clock.advance(self.spec.tick_s)
        for e in self._events_by_tick.get(tick, ()):
            self._apply_event(e, tick)
        self.backend.tick()
        try:
            self.cc.load_monitor.task_runner.run_sampling_once(
                end_ms=self.clock.now_ms())
        except Exception:  # noqa: BLE001 — a faulted sampling interval is
            # part of the scenario, not a harness error
            LOG.debug("simulated sampling tick failed", exc_info=True)
        fixes_before = mgr.state()["metrics"]["numSelfHealingStarted"]
        mgr.run_due(self.clock.now_s())
        self.score.anomalies_handled += mgr.drain_anomalies()
        self.cc.executor.await_completion(timeout_s=60.0)
        self.score.fixes_started += \
            mgr.state()["metrics"]["numSelfHealingStarted"] - fixes_before
        degraded = False
        if self._probe_every and tick and tick % self._probe_every == 0:
            degraded = self._probe_proposals(tick)
        replica_moves, leader_moves, bytes_mb = \
            self._observe_churn(self._snapshot())
        bal = self.cc.goal_violation_detector.balancedness_score \
            if self.cc.goal_violation_detector._last_result is not None \
            else None
        healthy = self._healthy()
        # Heal-ledger cross-validation anchor: the twin feeds the ledger
        # the SAME per-tick health observation the score closes its
        # HealEvents with, so ledger heal durations and ScenarioScore
        # time-to-heal share one closing tick (observation only — the
        # score JSON and trajectory are byte-identical ledger on/off).
        self.cc.heal_ledger.observe_health(healthy)
        self.score.observe_tick(tick, bal, replica_moves, leader_moves,
                                bytes_mb, healthy=healthy,
                                degraded=degraded)

    def advance(self, ticks: int) -> None:
        """Run ``ticks`` simulated ticks without the run()-level scoring
        wrap-up — the futures engine's advance-to-decision-point
        primitive (futures/evaluator.py builds one twin per candidate
        future, advances it here with detection disabled, and batches
        the decision solves)."""
        for tick in range(int(ticks)):
            self.run_tick(tick)

    def run(self) -> ScenarioResult:
        from ..utils.flight_recorder import FLIGHT, summarize_passes
        from ..utils.tracing import TRACER
        # ccsa: ok[CCSA004] host wall-clock for the scenario_run timer
        # sensor only — never enters the event stream or the score JSON,
        # so byte-identical replay is unaffected
        t0 = time.perf_counter()
        # Flight-recorder window for THIS scenario's solves: the marker
        # bounds passes_since to what the twin itself drove (the host's
        # own passes closed before the marker are excluded; the recorder
        # is process-global, so a concurrent host solve could still land
        # in the window — scenario runs are sequential in practice).
        flight_marker = FLIGHT.marker()
        with TRACER.span("scenario.run", operation="scenario",
                         scenario=self.spec.name, seed=self.seed,
                         ticks=self.spec.ticks) as sp:
            for tick in range(self.spec.ticks):
                self.run_tick(tick)
            counts = self.cc.executor.execution_state()["taskCounts"]
            self.score.dead_letters = sum(
                by_state.get("abandoned", 0) for by_state in counts.values())
            if self.chaos is not None:
                self.score.faults_injected = self.chaos.schedule.faults_injected
            if FLIGHT.enabled:
                sf = summarize_passes(FLIGHT.passes_since(flight_marker))
                # Drop the dispatch count: on the bounded path the
                # AdaptiveDispatch controller partitions the same total
                # rounds into a WALL-CLOCK-dependent number of dispatches,
                # and the score JSON must stay byte-identical at one seed.
                # Rounds, moves, and densities are budget-partitioning-
                # invariant (the megastep trajectory contract). The
                # per-round-derived fields (killAttribution, per-goal
                # violationTrajectory) are invariant only while every
                # dispatch's rounds fit the ring — i.e. while
                # solver.flight.recorder.ring.rounds (128) >= the pass's
                # max.solver.rounds: a longer dispatch overwrites its
                # oldest rows, and WHICH rows survive depends on the
                # partitioning. The simulator's config pins
                # max.solver.rounds=40, so the canonical library (and any
                # scenario keeping that default) is safely inside the
                # bound; overriding it past ring.rounds trades the
                # byte-identical guarantee for deeper logs.
                sf.pop("dispatches", None)
                self.score.solver_flight = sf
            sp.set(slo_violations=len(self.score.slo_violations()),
                   replica_moves=self.score.replica_moves,
                   heal_p95_ticks=self.score.time_to_heal_p95_ticks(),
                   dead_letters=self.score.dead_letters)
        self.score.emit_sensors()
        from ..utils.sensors import SENSORS
        # ccsa: ok[CCSA004] observability-only wall measurement (see t0)
        wall = time.perf_counter() - t0
        SENSORS.record_timer("scenario_run", wall,
                             labels={"scenario": self.spec.name})
        final = {f"{t}-{p}": sorted(st.replicas) for (t, p), st in
                 sorted(self.backend.describe_partitions().items())}
        return ScenarioResult(
            spec=self.spec, seed=self.seed, score=self.score,
            events=[e.as_dict() for e in self.events],
            final_assignment=final, wall_s=wall)


# ---------------------------------------------------------------------------
# Canonical scenario library
# ---------------------------------------------------------------------------

def _topic_churn_generator(seed: int, spec: ScenarioSpec,
                           ) -> list[ScenarioEvent]:
    """Seeded topic churn: every 5 ticks create, expand, or delete a
    churn-owned topic. Pure in (seed, spec): the symbolic topic registry
    is replayed inside the generator, so the stream never depends on
    simulator state."""
    out: list[ScenarioEvent] = []
    live: list[tuple[str, int]] = []  # (topic, partitions)
    n = 0
    for tick in range(5, spec.ticks - 5, 5):
        u = _hash01(seed, "churn", tick)
        if live and u < 0.3:
            i = zlib.crc32(f"{seed}:pick:{tick}".encode()) % len(live)
            topic, _parts = live.pop(i)
            out.append(ScenarioEvent(tick, "delete_topic", {"topic": topic}))
        elif live and u < 0.55:
            i = zlib.crc32(f"{seed}:grow:{tick}".encode()) % len(live)
            topic, parts = live[i]
            live[i] = (topic, parts + 4)
            out.append(ScenarioEvent(tick, "expand_partitions",
                                     {"topic": topic, "to": parts + 4}))
        else:
            topic = f"churn{n}"
            n += 1
            live.append((topic, 8))
            out.append(ScenarioEvent(tick, "create_topic",
                                     {"topic": topic, "partitions": 8}))
    return out


CANONICAL_SCENARIOS: dict[str, ScenarioSpec] = {s.name: s for s in (
    ScenarioSpec(
        name="broker_loss_drift",
        description="Diurnal load drift, then broker 5 dies at tick 23 "
                    "(off the detection cadence, so detection latency is "
                    "part of time-to-heal): the loop must detect, "
                    "escalate, and relocate every hosted replica.",
        drift=DriftSpec(amplitude=0.4, period_ticks=60),
        events=(ScenarioEvent(23, "kill_broker", {"broker": 5}),)),
    ScenarioSpec(
        name="rolling_maintenance",
        description="Rolling drain: maintenance plans remove then re-add "
                    "brokers one at a time, with one disk failing and "
                    "being drained mid-roll (JBOD intra-broker moves).",
        ticks=100,
        jbod_dirs=2,
        # A drained broker keeps balancedness at the one-goal-violated
        # plateau (62.26) for the whole drain window BY DESIGN — the floor
        # tolerates the scripted degradation; breaching 60 (or failing to
        # return to 100 by scenario end, pinned in tests) is the
        # regression signal.
        config_overrides={"scenario.slo.balancedness.min": 60.0},
        events=(
            ScenarioEvent(10, "maintenance",
                          {"plan": "REMOVE_BROKER", "brokers": [1]}),
            ScenarioEvent(35, "maintenance",
                          {"plan": "ADD_BROKER", "brokers": [1]}),
            ScenarioEvent(45, "kill_logdir", {"broker": 3, "logdir": "/d0"}),
            ScenarioEvent(46, "remove_disks", {"broker": 3,
                                               "logdir": "/d0"}),
            ScenarioEvent(55, "maintenance",
                          {"plan": "REMOVE_BROKER", "brokers": [2]}),
            ScenarioEvent(80, "maintenance",
                          {"plan": "ADD_BROKER", "brokers": [2]}),
        )),
    ScenarioSpec(
        name="multi_az_failure",
        description="Both brokers of one AZ (rack az2) fail at tick 25 "
                    "and return at tick 85: rack-aware self-healing under "
                    "a whole-fault-domain outage, then rebalance back "
                    "onto the revived AZ once the removal-history "
                    "retention (30 sim-minutes here) lapses on the "
                    "injected clock.",
        ticks=110,
        # Sub-horizon retention: self-healing the dead AZ records brokers
        # 2/5 in the removal history; the revived AZ can only be refilled
        # after the history expires ON SIM TIME. (This scenario is what
        # caught the unbounded-history bug — a bare set excluded revived
        # brokers forever and goal-violation fixing reported "unfixable"
        # endlessly.)
        config_overrides={"removal.history.retention.time.ms": 1_800_000,
                          # Tolerate the scripted outage plateau (62.26
                          # while the AZ is down); recovery to 100 after
                          # revival is pinned in tests.
                          "scenario.slo.balancedness.min": 60.0},
        events=(
            ScenarioEvent(25, "kill_broker", {"broker": 2}),
            ScenarioEvent(25, "kill_broker", {"broker": 5}),
            ScenarioEvent(85, "revive_broker", {"broker": 2}),
            ScenarioEvent(85, "revive_broker", {"broker": 5}),
        )),
    ScenarioSpec(
        name="topic_churn_storm",
        description="Seeded create/expand/delete churn every 5 ticks: "
                    "the model pipeline and goal chain must track a "
                    "partition table that never sits still.",
        ticks=100,
        # Under sustained churn the table never converges — balancedness
        # hovers at the mild-violation plateau between fix cycles, which
        # is the scenario's POINT; the floor only flags deeper damage.
        config_overrides={"scenario.slo.balancedness.min": 60.0},
        generators=(_topic_churn_generator,)),
    ScenarioSpec(
        name="capacity_heterogeneity",
        description="Half the fleet has 2x capacity, sized so "
                    "DiskCapacityGoal must place by headroom rather than "
                    "count, while topic t0 runs 3x hot mid-scenario.",
        ticks=90,
        capacity_skew=2.0,
        # Usable disk on the base-capacity half = 0.8 threshold × 1e5 =
        # 80 GB vs a ~81 GB round-robin footprint: the capacity goal must
        # actually shed replicas toward the 2x half.
        disk_capacity_mb=1.0e5,
        drift=DriftSpec(amplitude=0.2, period_ticks=45),
        config_overrides={
            "anomaly.detection.goals": [
                "cruise_control_tpu.analyzer.goals.RackAwareGoal",
                "cruise_control_tpu.analyzer.goals.DiskCapacityGoal",
                "cruise_control_tpu.analyzer.goals.ReplicaDistributionGoal",
            ],
            # The round-robin start deliberately violates disk capacity on
            # the base half (scored ~40.6 until the shed completes);
            # recovery to 100 is pinned in tests.
            "scenario.slo.balancedness.min": 35.0},
        events=(
            ScenarioEvent(20, "hotspot", {"topic": "t0", "factor": 3.0}),
            ScenarioEvent(60, "clear_hotspot", {"topic": "t0"}),
        )),
    ScenarioSpec(
        name="diurnal_forecast_capacity",
        description="A concentrated hot topic under a rising diurnal "
                    "ramp pushes one broker over the network-inbound "
                    "capacity threshold near the peak — the FORECASTABLE "
                    "violation predictive rebalancing (round 19) is "
                    "scored against. Default run is the REACTIVE arm "
                    "(forecast off): detect at the crossing, heal after. "
                    "The bench --forecast stage replays it with "
                    "forecast.enabled (+ the proactive-fix opt-in) and "
                    "compares time-to-heal / SLO-violation ticks / "
                    "moves-per-simhour between the arms at pinned seeds.",
        ticks=48,
        drift=DriftSpec(amplitude=0.6, period_ticks=48),
        # The hot broker's MODEL (17-window rolling mean) peaks ≈ 29.3k
        # NW_IN around tick 20 (seed 0); limit = 0.8 × 35.625k = 28.5k,
        # crossed around tick 18-19 — the forecaster's 16-window fit at
        # horizon 6 sees the crossing coming several ticks earlier.
        nw_in_capacity_mb=35_625.0,
        config_overrides={
            "goals": [
                "cruise_control_tpu.analyzer.goals.RackAwareGoal",
                "cruise_control_tpu.analyzer.goals.ReplicaCapacityGoal",
                "cruise_control_tpu.analyzer.goals."
                "NetworkInboundCapacityGoal",
                "cruise_control_tpu.analyzer.goals."
                "ReplicaDistributionGoal",
            ],
            "anomaly.detection.goals": [
                "cruise_control_tpu.analyzer.goals.RackAwareGoal",
                "cruise_control_tpu.analyzer.goals."
                "NetworkInboundCapacityGoal",
                "cruise_control_tpu.analyzer.goals."
                "ReplicaDistributionGoal",
            ],
            # Per-tick detection: the reactive arm's heal latency is
            # detection-bounded, not cadence-bounded — the honest
            # comparison baseline for the proactive arm.
            "anomaly.detection.interval.ms": 60_000,
            # 17 windows = 16 stable: the model's rolling mean spans
            # exactly the forecaster's 16-window fit, so the projected
            # model view aligns with what the detector will see.
            "num.partition.metrics.windows": 17,
            # The capacity breach is the scenario's POINT: the floor
            # tolerates the reactive arm's violation window (the bench
            # stage compares the arms on the strict trajectory instead).
            "scenario.slo.balancedness.min": 40.0},
        events=(
            ScenarioEvent(1, "create_topic", {"topic": "hot",
                                              "partitions": 4}),
            ScenarioEvent(2, "hotspot", {"topic": "hot", "factor": 8.0}),
        )),
    ScenarioSpec(
        name="chaos_drift",
        description="Combined chaos + drift: injected admin/sampler "
                    "faults and a broker loss under diurnal ramp; faults "
                    "stop at tick 90 and the run must converge clean.",
        chaos_fault_rate=0.08,
        drift=DriftSpec(amplitude=0.5, period_ticks=60),
        events=(
            ScenarioEvent(33, "kill_broker", {"broker": 4}),
            ScenarioEvent(90, "stop_faults", {}),
        )),
)}


def run_scenario(scenario: str | ScenarioSpec, seed: int = 0,
                 ticks: int | None = None,
                 config_overrides: Mapping | None = None) -> ScenarioResult:
    """Run one scenario end to end and return its scored result. ``ticks``
    overrides the spec's horizon (the what-if endpoint and CI matrix use
    shortened replays); everything else about the spec is immutable."""
    if isinstance(scenario, str):
        try:
            spec = CANONICAL_SCENARIOS[scenario]
        except KeyError:
            raise ValueError(
                f"unknown scenario {scenario!r}; expected one of "
                f"{', '.join(sorted(CANONICAL_SCENARIOS))}") from None
    else:
        spec = scenario
    if ticks is not None:
        spec = dataclasses.replace(spec, ticks=int(ticks))
    sim = ClusterSimulator(spec, seed=seed,
                           config_overrides=config_overrides)
    return sim.run()
