"""Deterministic fault-injection harnesses (chaos testing).

Not test-only code: ``chaos.ChaosAdminBackend`` can wrap the production
admin backend via the ``chaos.enabled`` config key for game-day drills,
and bench.py drives a faulted executor cycle through it for the
``degraded_cycle_s`` extra.
"""

from .chaos import (
    ChaosAdminBackend, ChaosSampler, ChaosTimeout, ChaosTransientError,
    FaultSchedule, run_faulted_executor_cycle,
)

__all__ = [
    "ChaosAdminBackend", "ChaosSampler", "ChaosTimeout",
    "ChaosTransientError", "FaultSchedule", "run_faulted_executor_cycle",
]
