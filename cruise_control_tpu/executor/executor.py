"""The Executor: applies optimization proposals to the live cluster.

Reference parity: executor/Executor.java (2,223 LoC). Lifecycle:
``execute_proposals`` reserves execution, expands proposals into tasks, and
a background runnable works the three phases in order — inter-broker moves,
intra-broker moves, leadership — batching per progress-check interval,
polling completion, marking tasks on dead brokers DEAD, and re-submitting
leftovers (Executor.java:1291 ProposalExecutionRunnable, :1436-1497 phase
order, :2211 leftover re-execution). Stop signals abort pending work and
cancel in-flight reassignments (userTriggeredStopExecution:1139).
"""

from __future__ import annotations

import enum
import threading
import time
from typing import Callable, Sequence

from ..analyzer.proposals import ExecutionProposal
from ..utils.heal_ledger import NO_HEAL, current_heal
from ..utils.resilience import RetryPolicy, call_with_resilience
from .admin import AdminBackend
from .concurrency import ConcurrencyCaps, ExecutionConcurrencyManager
from .min_isr import TopicMinIsrCache, cluster_isr_state
from .notifier import ExecutorNotifier, LoggingExecutorNotifier
from .planner import ExecutionTaskPlanner
from .strategy import ReplicaMovementStrategy
from .task import (
    ExecutionTask, ExecutionTaskManager, TaskState, TaskType,
)
from .throttle import _KEEP as _KEEP_RATE, ReplicationThrottleHelper


class ExecutorState(enum.Enum):
    """Executor.State (ExecutorState.java)."""

    NO_TASK_IN_PROGRESS = "NO_TASK_IN_PROGRESS"
    STARTING_EXECUTION = "STARTING_EXECUTION"
    INTER_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS = \
        "INTER_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS"
    INTRA_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS = \
        "INTRA_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS"
    LEADER_MOVEMENT_TASK_IN_PROGRESS = "LEADER_MOVEMENT_TASK_IN_PROGRESS"
    STOPPING_EXECUTION = "STOPPING_EXECUTION"


class OngoingExecutionError(RuntimeError):
    """An execution is already in progress (Executor's IllegalState)."""


class OngoingExternalReassignmentError(RuntimeError):
    """The cluster has partition reassignments this executor did not start
    (ExecutionUtils.ongoingPartitionReassignments sanity check): refuse to
    stack an execution on top unless told to stop the external agent or to
    adopt the in-flight work."""


class Executor:
    def __init__(self, admin: AdminBackend,
                 caps: ConcurrencyCaps | None = None,
                 strategy: ReplicaMovementStrategy | None = None,
                 progress_check_interval_s: float = 0.05,
                 replication_throttle: int | None = None,
                 task_timeout_s: float = 3600.0,
                 on_sampling_mode_change: Callable[[bool], None] | None = None,
                 synchronous: bool = False,
                 notifier: ExecutorNotifier | None = None,
                 adjuster_enabled: bool = True,
                 adjuster_interval_s: float = 1.0,
                 adjuster_config: "ConcurrencyAdjusterConfig | None" = None,
                 broker_metrics_supplier: Callable[[], dict] | None = None,
                 inter_rate_alert_mb_s: float = 0.0,
                 intra_rate_alert_mb_s: float = 0.0,
                 retry_policy: RetryPolicy | None = None,
                 dead_letter_attempts: int = 3):
        self._admin = admin
        # Resilience (round 9): every admin call runs under the retry
        # policy (None = bare calls, the pre-round-9 behavior); a batch
        # whose SUBMISSION keeps failing transiently is requeued and,
        # after ``dead_letter_attempts`` failed submissions, dead-
        # lettered to the EXECUTION_ABANDONED terminal state instead of
        # hanging the execution until the global task timeout.
        self._retry_policy = retry_policy
        self._dead_letter_attempts = max(1, dead_letter_attempts)
        self._submit_attempts: dict[int, int] = {}
        # Separate budget for COMPLETION-VERIFY failures (the submission
        # reached the cluster; the read-back did not): exhausting it
        # DEAD-marks, never dead-letters — see _requeue_or_kill_unverified.
        self._verify_attempts: dict[int, int] = {}
        self._concurrency = ExecutionConcurrencyManager(caps, adjuster_config)
        # ConcurrencyAdjuster (Executor.java:465-683): every interval the
        # poll loop re-evaluates broker health, (At/Under)MinISR state, and
        # broker metric limits (via ``broker_metrics_supplier``, typically
        # the LoadMonitor's latest broker window) and re-tunes the caps.
        self._adjuster_enabled = adjuster_enabled
        self._adjuster_interval_s = adjuster_interval_s
        self._min_isr_cache = TopicMinIsrCache()
        self._last_adjust = 0.0
        self._broker_metrics_supplier = broker_metrics_supplier
        # Sticky min-ISR window (concurrency.adjuster.num.min.isr.check):
        # pressure seen in ANY of the last N ticks keeps the decrease
        # signal active, so a transiently-recovered ISR doesn't bounce
        # concurrency straight back up.
        from collections import deque
        n_checks = (adjuster_config.num_min_isr_check
                    if adjuster_config else 5)
        self._min_isr_window: deque[bool] = deque(maxlen=max(1, n_checks))
        # (inter|intra).broker.replica.movement.rate.alerting.threshold:
        # a finished execution whose average data movement rate fell below
        # these MB/s marks is reported as slow (0 = disabled).
        self._inter_rate_alert = inter_rate_alert_mb_s
        self._intra_rate_alert = intra_rate_alert_mb_s
        self._strategy = strategy
        self._interval = progress_check_interval_s
        # Per-execution execution_progress_check_interval_ms override;
        # cleared in _finish_run.
        self._interval_override: float | None = None
        self._task_timeout_s = task_timeout_s
        self._throttle = ReplicationThrottleHelper(admin, replication_throttle)
        # Executor.java:1408-1424: pause/restore metric sampling around
        # execution so in-flight moves don't pollute the load model.
        self._on_sampling_mode_change = on_sampling_mode_change
        self._synchronous = synchronous
        self._notifier = notifier or LoggingExecutorNotifier()

        self._lock = threading.Lock()
        self._state = ExecutorState.NO_TASK_IN_PROGRESS
        self._stop_requested = threading.Event()
        self._thread: threading.Thread | None = None
        self._task_manager: ExecutionTaskManager | None = None
        self._planner: ExecutionTaskPlanner | None = None
        self._uuid: str | None = None
        self._history: list[dict] = []
        self._caps_snapshot: ConcurrencyCaps | None = None
        self._override_dims: set[str] = set()
        # Heal ledger: the correlation handle captured at
        # execute_proposals time (the execution runnable is a fresh
        # thread, so the ambient ContextVar would not cross into it).
        self._heal = NO_HEAL

    # ---- public surface ---------------------------------------------------
    @property
    def _poll_interval(self) -> float:
        return self._interval if self._interval_override is None \
            else self._interval_override

    @property
    def state(self) -> ExecutorState:
        return self._state

    def has_ongoing_execution(self) -> bool:
        return self._state is not ExecutorState.NO_TASK_IN_PROGRESS

    def execute_proposals(self, proposals: Sequence[ExecutionProposal],
                          uuid: str = "",
                          stop_external_agent: bool = False,
                          strategy: ReplicaMovementStrategy | None = None,
                          concurrency_overrides: dict | None = None,
                          progress_check_interval_s: float | None = None,
                          replication_throttle: int | None = None,
                          throttle_excluded_brokers: Sequence[int] = (),
                          ) -> None:
        """Start executing; raises OngoingExecutionError when busy
        (Executor.executeProposals:809). Reassignments already in flight
        that this executor did not start are EXTERNAL: refused by default
        (ExecutionUtils.ongoingPartitionReassignments sanity), cancelled
        first when ``stop_external_agent`` (maybeStopExternalAgent:1261).

        ``strategy``/``concurrency_overrides`` apply to THIS execution only
        (the reference resets requested concurrency when the execution
        finishes); the caps snapshot is restored in ``_finish_run``.
        ``progress_check_interval_s`` (execution_progress_check_interval_ms
        request param), ``replication_throttle`` (rate override; None =
        keep the configured rate) and ``throttle_excluded_brokers``
        (throttle_added_broker/throttle_removed_broker=false) likewise
        last for this execution only."""
        with self._lock:
            if self.has_ongoing_execution():
                raise OngoingExecutionError(
                    f"execution {self._uuid!r} still in progress")
            # Deliberately NOT retried: this runs under self._lock, and
            # backoff sleeps here would block stop_execution/state reads
            # for the whole retry budget. A transient failure fails the
            # request; the caller retries from outside the lock.
            external = self._admin.list_reassigning_partitions()
            if external:
                if not stop_external_agent:
                    raise OngoingExternalReassignmentError(
                        f"{len(external)} partition(s) already reassigning "
                        "(external agent?): pass stop_external_agent=True "
                        "to cancel them, or adopt_ongoing_reassignments() "
                        "to track them to completion")
                self._admin.cancel_partition_reassignments(external)
            self._state = ExecutorState.STARTING_EXECUTION
            self._stop_requested.clear()
            # Stale pressure from a PREVIOUS execution must not suppress
            # this one's starting concurrency.
            self._min_isr_window.clear()
            self._uuid = uuid
            if progress_check_interval_s is not None:
                self._interval_override = progress_check_interval_s
            if replication_throttle is not None or throttle_excluded_brokers:
                self._throttle.begin_execution(
                    rate_override=(replication_throttle
                                   if replication_throttle is not None
                                   else _KEEP_RATE),
                    excluded_brokers=throttle_excluded_brokers)
            if concurrency_overrides:
                self._caps_snapshot = self._concurrency.snapshot()
                self._override_dims = set(concurrency_overrides)
                self.set_requested_concurrency(**concurrency_overrides)
            self._task_manager = ExecutionTaskManager()
            self._planner = ExecutionTaskPlanner(strategy or self._strategy)
            self._submit_attempts = {}
            self._verify_attempts = {}
            tasks = self._task_manager.tasks_from_proposals(proposals)
            self._planner.add_tasks(tasks, self._admin)
            # A self-healing fix's execution attributes its submit/
            # progress/timeout/dead-letter phases to the heal chain
            # ambient on the SUBMITTING thread (NO_HEAL otherwise).
            self._heal = current_heal()
            self._heal.phase("execution_started", uuid=uuid,
                             numProposals=len(proposals),
                             numTasks=len(tasks))
        if self._synchronous:
            self._run()
        else:
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name=f"proposal-execution-{uuid}")
            self._thread.start()

    def adopt_ongoing_reassignments(self, uuid: str = "adopted") -> int:
        """Recover after a restart: observe reassignments already in flight
        (from a previous executor life or an external tool), reconstruct
        their proposals from the cluster's adding/removing sets, and track
        them to completion with the normal poll loop — without re-submitting
        anything (Executor.java:1238 listPartitionsBeingReassigned recovery).
        Returns the number of adopted tasks (0 = nothing to adopt)."""
        with self._lock:
            if self.has_ongoing_execution():
                raise OngoingExecutionError(
                    f"execution {self._uuid!r} still in progress")
            parts = self._admin.describe_partitions()
            adopted: list[ExecutionProposal] = []
            for key, p in parts.items():
                if not p.is_reassigning:
                    continue
                target = tuple(b for b in p.replicas if b not in p.removing)
                original = tuple(b for b in p.replicas if b not in p.adding)
                # Leadership-neutral: the broker elects the new leader
                # itself when the current one sits on a removed replica, and
                # we cannot predict which (it need not be target[0]).
                # new_leader = -1 records "no leadership action tracked" —
                # guessing a leader here would write a wrong new_leader into
                # history/state (VERDICT r2 weak #5).
                adopted.append(ExecutionProposal(
                    topic=p.topic, partition=p.partition,
                    old_leader=p.leader, old_replicas=original,
                    new_replicas=target, new_leader=-1))
            if not adopted:
                return 0
            self._state = ExecutorState.STARTING_EXECUTION
            self._stop_requested.clear()
            self._min_isr_window.clear()
            self._uuid = uuid
            self._task_manager = ExecutionTaskManager()
            self._planner = ExecutionTaskPlanner(self._strategy)
            tasks = self._task_manager.tasks_from_proposals(adopted)
        run = lambda: self._run_adopted(tasks)  # noqa: E731
        if self._synchronous:
            run()
        else:
            self._thread = threading.Thread(target=run, daemon=True,
                                            name=f"adopted-execution-{uuid}")
            self._thread.start()
        return len(tasks)

    def _run_adopted(self, tasks: list[ExecutionTask]) -> None:
        """Poll already-submitted reassignments to completion (no new
        alterPartitionReassignments calls)."""
        from ..utils.tracing import TRACER
        with TRACER.span("executor.execute", operation="execution",
                         uuid=self._uuid, adopted=True):
            self._run_adopted_inner(tasks)

    def _run_adopted_inner(self, tasks: list[ExecutionTask]) -> None:
        t0 = time.time()
        tracker = self._task_manager.tracker
        in_flight = [t for t in tasks
                     if t.task_type is TaskType.INTER_BROKER_REPLICA_ACTION]
        with self._lock:
            if not self._stop_requested.is_set():
                self._state = \
                    ExecutorState.INTER_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS
        for task in in_flight:
            tracker.transition(task, task.in_progress)
        stopped = False
        try:
            # Adopted moves pollute the load model like any others: pause
            # sampling for the duration (Executor.java:1408-1424).
            if self._on_sampling_mode_change:
                self._on_sampling_mode_change(True)
            while in_flight:
                if self._stop_requested.is_set():
                    self._abort_pending_and_inflight(in_flight)
                    stopped = True
                    break
                time.sleep(self._poll_interval)
                self._poll_inter_broker(in_flight)
        finally:
            if self._on_sampling_mode_change:
                self._on_sampling_mode_change(False)
            self._finish_run(t0, stopped)

    def _finish_run(self, t0: float, stopped: bool) -> None:
        tm = self._task_manager
        summary = {
            "uuid": self._uuid,
            "stopped": stopped or self._stop_requested.is_set(),
            "durationS": round(time.time() - t0, 3),
            "taskCounts": tm.tracker.counts() if tm else {},
        }
        heal, self._heal = self._heal, NO_HEAL
        abandoned = sum(by_state.get("abandoned", 0)
                        for by_state in summary["taskCounts"].values())
        heal.phase("execution_finished", stopped=bool(summary["stopped"]),
                   taskCounts=summary["taskCounts"])
        if abandoned:
            # Dead-lettered submissions are a documented heal terminal:
            # the control plane never got the whole fix through.
            heal.resolve("dead_lettered", numTasks=abandoned)
        self._check_movement_rate(summary)
        self._history.append(summary)
        # Execution sensors (Executor.java:145-148,346).
        from ..utils.sensors import SENSORS
        from ..utils.tracing import TRACER
        # Outcome attributes land on the ambient executor.execute span
        # (opened in _run/_run_adopted around this call).
        TRACER.annotate(
            stopped=bool(summary["stopped"]),
            tasks=sum(n for by_state in summary["taskCounts"].values()
                      for n in by_state.values()))
        SENSORS.record_timer("executor_execution", time.time() - t0)
        SENSORS.count("executor_executions_stopped"
                      if summary["stopped"] else "executor_executions_finished")
        for task_type, by_state in summary["taskCounts"].items():
            for task_state, n in by_state.items():
                SENSORS.count("executor_tasks", n,
                              labels={"type": task_type, "state": task_state})
        # Reset state FIRST: a raising notifier must not wedge the executor
        # in an in-progress state forever.
        with self._lock:
            self._state = ExecutorState.NO_TASK_IN_PROGRESS
            self._interval_override = None
            if self._caps_snapshot is not None:
                self._concurrency.restore(self._caps_snapshot)
                self._caps_snapshot = None
                self._override_dims = set()
        try:
            if summary["stopped"]:
                self._notifier.on_execution_stopped(summary)
            else:
                self._notifier.on_execution_finished(summary)
        except Exception:  # noqa: BLE001 - notification is best-effort
            import logging

            logging.getLogger(__name__).warning(
                "executor notifier failed", exc_info=True)

    def _check_movement_rate(self, summary: dict) -> None:
        """Slow-execution alerting ((inter|intra).broker.replica.movement.
        rate.alerting.threshold): average MB/s of completed replica moves
        below the threshold is recorded in the summary and counted as a
        sensor — operators watch for stuck/throttled executions."""
        tm = self._task_manager
        duration = summary.get("durationS") or 0
        if tm is None or duration <= 0:
            return
        from ..utils.sensors import SENSORS
        for task_type, threshold, key in (
                (TaskType.INTER_BROKER_REPLICA_ACTION,
                 self._inter_rate_alert, "interBroker"),
                (TaskType.INTRA_BROKER_REPLICA_ACTION,
                 self._intra_rate_alert, "intraBroker")):
            if threshold <= 0:
                continue
            moved_mb = sum(
                t.proposal.data_to_move_mb
                * max(1, len(t.proposal.replicas_to_add))
                for t in tm.tracker.tasks_in(task_type, TaskState.COMPLETED))
            if moved_mb <= 0:
                continue
            rate = moved_mb / duration
            summary[f"{key}MovementRateMBps"] = round(rate, 3)
            if rate < threshold:
                summary[f"{key}MovementRateSlow"] = True
                SENSORS.count("executor_slow_movement_rate",
                              labels={"type": task_type.value})
                import logging
                logging.getLogger(__name__).warning(
                    "%s movement rate %.3f MB/s below alerting threshold "
                    "%.3f MB/s (execution %s)", key, rate, threshold,
                    self._uuid)

    def stop_execution(self) -> None:
        """User-triggered stop (Executor.userTriggeredStopExecution:1139):
        drop pending tasks, cancel in-flight reassignments. Takes the lock so
        a finishing runnable can't be resurrected into STOPPING."""
        with self._lock:
            if not self.has_ongoing_execution():
                return
            self._state = ExecutorState.STOPPING_EXECUTION
            self._stop_requested.set()

    def stop_external_reassignments(self) -> int:
        """Cancel reassignments this executor did not start
        (maybeStopExternalAgent:1261). Holds the lock across the
        ongoing-execution check and the cancel, so a concurrently starting
        internal execution (which reserves state under the same lock before
        submitting) can never be mistaken for an external agent."""
        with self._lock:
            if self.has_ongoing_execution():
                return 0
            # Not retried: runs under self._lock (see execute_proposals).
            external = self._admin.list_reassigning_partitions()
            if external:
                self._admin.cancel_partition_reassignments(external)
            return len(external)

    def await_completion(self, timeout_s: float = 60.0) -> bool:
        t = self._thread
        if t is not None:
            t.join(timeout_s)
            return not t.is_alive()
        return True

    def execution_state(self, history_limit: int = 5) -> dict:
        tm = self._task_manager
        return {
            "state": self._state.value,
            "uuid": self._uuid,
            "taskCounts": tm.tracker.counts() if tm else {},
            "concurrency": self._concurrency.state(),
            "recentHistory": self._history[-history_limit:],
        }

    def adjust_concurrency(self, cluster_healthy: bool,
                           has_under_min_isr: bool) -> None:
        self._concurrency.adjust(cluster_healthy, has_under_min_isr)


    def set_requested_concurrency(self, inter_broker_per_broker: int | None = None,
                                  intra_broker_per_broker: int | None = None,
                                  leadership_cluster: int | None = None,
                                  cluster_inter_broker: int | None = None,
                                  leadership_per_broker: int | None = None,
                                  ) -> dict:
        """Operator concurrency override
        (Executor.setRequestedExecutionConcurrency)."""
        caps = self._concurrency._caps
        if inter_broker_per_broker is not None:
            caps.inter_broker_per_broker = inter_broker_per_broker
        if intra_broker_per_broker is not None:
            caps.intra_broker_per_broker = intra_broker_per_broker
        if leadership_cluster is not None:
            caps.leadership_cluster = leadership_cluster
        if cluster_inter_broker is not None:
            # max_partition_movements_in_cluster per-request override
            # (ParameterUtils.MAX_PARTITION_MOVEMENTS_IN_CLUSTER_PARAM).
            caps.cluster_inter_broker = cluster_inter_broker
        if leadership_per_broker is not None:
            # broker_concurrent_leader_movements per-request override.
            caps.leadership_per_broker = leadership_per_broker
        return self._concurrency.state()

    def set_concurrency_adjuster_for(self, concurrency_type: str,
                                     enabled: bool) -> bool:
        """ADMIN (en|dis)able_concurrency_adjuster_for toggle."""
        return self._concurrency.set_adjuster_enabled(concurrency_type,
                                                      enabled)

    def set_min_isr_based_adjustment(self, enabled: bool) -> bool:
        """ADMIN min_isr_based_concurrency_adjustment toggle."""
        return self._concurrency.set_min_isr_based_adjustment(enabled)

    def _set_phase(self, phase: ExecutorState) -> None:
        # Never overwrite a user-requested STOPPING state from the worker.
        with self._lock:
            if not self._stop_requested.is_set():
                self._state = phase

    # ---- resilience helpers (round 9) ------------------------------------
    def _admin_call(self, op: str, fn):
        """One admin-backend call under the retry policy (bare when no
        policy is configured — the zero-overhead path)."""
        return call_with_resilience(op, fn, policy=self._retry_policy)

    def _notify_event(self, name: str, payload: dict) -> None:
        """Best-effort optional notifier event (on_task_timeout /
        on_tasks_abandoned): a custom notifier without the round-9
        methods — or one that raises — must not affect execution."""
        fn = getattr(self._notifier, name, None)
        if fn is None:
            return
        try:
            fn(payload)
        except Exception:  # noqa: BLE001 — notification is best-effort
            import logging
            logging.getLogger(__name__).warning(
                "executor notifier %s failed", name, exc_info=True)

    def _requeue_or_abandon(self, batch: list[ExecutionTask]) -> None:
        """A batch whose submission failed past the retry policy: count
        the failed submission per task, requeue the survivors into the
        planner (they re-dequeue under normal concurrency headroom) and
        dead-letter tasks past the attempt budget to EXECUTION_ABANDONED
        with a notifier event."""
        assert self._planner is not None and self._task_manager is not None
        tracker = self._task_manager.tracker
        retry: list[ExecutionTask] = []
        abandoned: list[ExecutionTask] = []
        for task in batch:
            n = self._submit_attempts.get(task.execution_id, 0) + 1
            self._submit_attempts[task.execution_id] = n
            if n >= self._dead_letter_attempts:
                tracker.transition(task, task.abandon)
                abandoned.append(task)
            else:
                retry.append(task)
        from ..utils.sensors import SENSORS
        if abandoned:
            by_type: dict[str, int] = {}
            for t in abandoned:
                by_type[t.task_type.value] = by_type.get(t.task_type.value,
                                                         0) + 1
            for task_type, n in by_type.items():
                SENSORS.count("executor_tasks_abandoned", n,
                              labels={"type": task_type})
            self._notify_event("on_tasks_abandoned", {
                "uuid": self._uuid, "numTasks": len(abandoned),
                "byType": by_type,
                "taskIds": [t.execution_id for t in abandoned],
                "attempts": self._dead_letter_attempts})
            self._heal.phase("dead_letter", numTasks=len(abandoned),
                             byType=by_type)
        if retry:
            self._planner.add_tasks(retry, self._admin)

    def _requeue_or_kill_unverified(self, batch: list[ExecutionTask]) -> None:
        """Tasks whose SUBMISSION succeeded but whose completion could
        not be verified (the metadata read-back failed or was partial):
        requeue for re-verification — re-submitting a preferred-leader
        election is idempotent — and after the attempt budget DEAD-mark
        them. Never dead-letters: EXECUTION_ABANDONED means 'the control
        plane never got through', which would misreport work the cluster
        may well have applied."""
        assert self._planner is not None and self._task_manager is not None
        tracker = self._task_manager.tracker
        retry: list[ExecutionTask] = []
        killed = 0
        for task in batch:
            n = self._verify_attempts.get(task.execution_id, 0) + 1
            self._verify_attempts[task.execution_id] = n
            if n >= self._dead_letter_attempts:
                tracker.transition(task, task.in_progress)
                tracker.transition(task, task.kill)
                killed += 1
            else:
                retry.append(task)
        if killed:
            from ..utils.sensors import SENSORS
            SENSORS.count("executor_tasks_unverified", killed,
                          labels={"type": batch[0].task_type.value})
        if retry:
            self._planner.add_tasks(retry, self._admin)

    def _submit_batch(self, op: str, batch: list[ExecutionTask],
                      submit_fn) -> bool:
        """Run a batch submission under the retry policy; on final
        failure requeue/dead-letter the batch and return False (the
        phase loop continues — later polls pick the requeue up)."""
        try:
            self._admin_call(op, submit_fn)
            return True
        except Exception:  # noqa: BLE001 — transient classification done
            import logging
            logging.getLogger(__name__).warning(
                "%s submission failed after retries; requeueing %d task(s)",
                op, len(batch), exc_info=True)
            from ..utils.sensors import SENSORS
            SENSORS.count("executor_submit_failures", labels={"op": op})
            self._requeue_or_abandon(batch)
            return False

    def _task_timed_out(self, task: ExecutionTask, now: float) -> bool:
        """The ONE task-timeout predicate shared by the inter- and
        intra-broker polls (previously two near-identical inline
        blocks): true when the task overran ``task_timeout_s``, with a
        ``task_timeouts_total{type=}`` sensor and a notifier event."""
        if task.start_time_ms <= 0 \
                or now - task.start_time_ms / 1000 <= self._task_timeout_s:
            return False
        from ..utils.sensors import SENSORS
        SENSORS.count("task_timeouts", labels={"type": task.task_type.value})
        self._notify_event("on_task_timeout", task.to_dict())
        self._heal.phase("task_timeout", type=task.task_type.value,
                         executionId=task.execution_id)
        return True

    # ---- the proposal execution runnable ---------------------------------
    def _run(self) -> None:
        t0 = time.time()
        stopped = False
        # One span for the whole execution: batch_submit spans open on
        # this thread and MUST nest under it — parentless they would each
        # become a single-span trace and flood the tracer's ring.
        from ..utils.tracing import TRACER
        with TRACER.span("executor.execute", operation="execution",
                         uuid=self._uuid):
            try:
                if self._on_sampling_mode_change:
                    self._on_sampling_mode_change(True)
                stopped = not self._inter_broker_move_phase()
                if not stopped:
                    stopped = not self._intra_broker_move_phase()
                if not stopped:
                    stopped = not self._leadership_phase()
            finally:
                self._throttle.clear_throttles()
                if self._on_sampling_mode_change:
                    self._on_sampling_mode_change(False)
                self._finish_run(t0, stopped)

    def _abort_pending_and_inflight(self, in_flight: list[ExecutionTask]) -> None:
        assert self._planner is not None and self._task_manager is not None
        tracker = self._task_manager.tracker
        dropped = self._planner.clear()
        tracker.add(dropped)
        for task in dropped:
            tracker.transition(task, task.in_progress)
            tracker.transition(task, task.abort)
            tracker.transition(task, task.aborted)
        if in_flight:
            try:
                self._admin_call(
                    "admin.cancel_partition_reassignments",
                    lambda: self._admin.cancel_partition_reassignments(
                        [t.topic_partition for t in in_flight]))
            except Exception:  # noqa: BLE001 — stop must complete; the
                # cluster finishes the uncancelled moves on its own.
                import logging
                logging.getLogger(__name__).warning(
                    "cancel on stop failed", exc_info=True)
            for task in in_flight:
                tracker.transition(task, task.abort)
                tracker.transition(task, task.aborted)
                self._concurrency.release_inter_broker(
                    tuple(set(task.proposal.replicas_to_add)
                          | set(task.proposal.replicas_to_remove)))
            in_flight.clear()

    def _inter_broker_move_phase(self) -> bool:
        """Executor.interBrokerMoveReplicas:1603. Returns False if stopped."""
        assert self._planner is not None and self._task_manager is not None
        self._set_phase(ExecutorState.INTER_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS)
        tracker = self._task_manager.tracker
        in_flight: list[ExecutionTask] = []

        while True:
            if self._stop_requested.is_set():
                self._abort_pending_and_inflight(in_flight)
                return False

            # Submit as many ready tasks as concurrency allows.
            batch = self._planner.inter_broker_tasks(
                self._concurrency.inter_broker_headroom,
                max_total=self._concurrency.cluster_inter_broker_headroom())
            if batch:
                from ..utils.tracing import TRACER
                with TRACER.span("executor.batch_submit",
                                 type="INTER_BROKER_REPLICA_ACTION",
                                 tasks=len(batch)) as sp:
                    targets = {t.topic_partition: t.proposal.new_replicas
                               for t in batch}

                    def submit():
                        # Throttles inside the retried closure: altering
                        # the same config values twice is idempotent, and
                        # a throttle that failed alongside the submit must
                        # be re-applied with it.
                        self._throttle.set_throttles(batch)
                        self._admin.alter_partition_reassignments(targets)

                    if self._submit_batch(
                            "admin.alter_partition_reassignments",
                            batch, submit):
                        for task in batch:
                            tracker.transition(task, task.in_progress)
                            self._concurrency.acquire_inter_broker(
                                tuple(set(task.proposal.replicas_to_add)
                                      | set(task.proposal.replicas_to_remove)))
                        in_flight.extend(batch)
                        finished, total = tracker.progress()
                        self._heal.phase(
                            "execution_progress",
                            type="inter_broker", submitted=len(batch),
                            finished=finished, total=total)
                    else:
                        sp.set(submit_failed=True)

            if not in_flight and self._planner.num_pending(
                    TaskType.INTER_BROKER_REPLICA_ACTION) == 0:
                return True

            time.sleep(self._poll_interval)
            self._poll_inter_broker(in_flight)

    def _maybe_adjust_concurrency(self, parts, alive: set[int]) -> None:
        """One ConcurrencyAdjuster tick from the metadata snapshot the poll
        already fetched: under-min-ISR pressure halves caps, healthy state
        steps them back up (Executor.java:465-683, TopicMinIsrCache)."""
        if not self._adjuster_enabled:
            return
        now = time.time()
        if now - self._last_adjust < self._adjuster_interval_s:
            return
        self._last_adjust = now
        min_isr = self._min_isr_cache.min_isr_by_topic(
            self._admin, {p.topic for p in parts.values()})
        healthy, under = cluster_isr_state(parts, alive, min_isr)
        self._min_isr_window.append(under)
        sticky_under = any(self._min_isr_window)
        # Broker metric limits (Executor.java:465-683): latest broker
        # metrics from the monitor, counted against the adjuster's limits.
        violating = 0
        if self._broker_metrics_supplier is not None:
            try:
                violating = self._concurrency.adjuster_config \
                    .brokers_violating_limits(self._broker_metrics_supplier())
            except Exception:  # noqa: BLE001 — metrics are advisory
                import logging
                logging.getLogger(__name__).warning(
                    "broker metrics supplier failed", exc_info=True)
        # Dimensions carrying a per-execution OPERATOR override are frozen
        # (the reference skips user-requested dimensions); the others —
        # including the min-ISR safety step-down — keep adjusting.
        self._concurrency.adjust(healthy, sticky_under,
                                 frozen=frozenset(self._override_dims),
                                 brokers_violating_metric_limits=violating)

    def _poll_inter_broker(self, in_flight: list[ExecutionTask]) -> None:
        """waitForInterBrokerReplicaTasksToFinish: poll reassignment state,
        complete finished tasks, kill tasks stuck on dead destinations
        (ExecutionUtils.isInterBrokerReplicaActionDone)."""
        assert self._task_manager is not None
        tracker = self._task_manager.tracker
        try:
            parts = self._admin_call("admin.describe_partitions",
                                     self._admin.describe_partitions)
            alive = self._admin_call("admin.alive_brokers",
                                     self._admin.alive_brokers)
        except Exception:  # noqa: BLE001 — degrade: skip this poll round
            # A transiently unreachable control plane must not kill the
            # execution thread; the next poll interval retries.
            from ..utils.sensors import SENSORS
            SENSORS.count("executor_poll_failures")
            import logging
            logging.getLogger(__name__).warning(
                "executor poll failed; will retry next interval",
                exc_info=True)
            return
        self._maybe_adjust_concurrency(parts, alive)
        now = time.time()
        still: list[ExecutionTask] = []
        for task in in_flight:
            p = parts.get(task.topic_partition)
            done = p is not None and not p.is_reassigning \
                and set(p.replicas) == set(task.proposal.new_replicas)
            brokers = tuple(set(task.proposal.replicas_to_add)
                            | set(task.proposal.replicas_to_remove))
            if done:
                tracker.transition(task, task.completed)
                self._concurrency.release_inter_broker(brokers)
            elif any(b not in alive for b in task.proposal.replicas_to_add) \
                    or self._task_timed_out(task, now):
                # Destination died or task timed out: mark DEAD, cancel.
                try:
                    self._admin_call(
                        "admin.cancel_partition_reassignments",
                        lambda tp=task.topic_partition:
                        self._admin.cancel_partition_reassignments([tp]))
                except Exception:  # noqa: BLE001 — cancel is best-effort
                    import logging
                    logging.getLogger(__name__).warning(
                        "cancel of %s failed", task.topic_partition,
                        exc_info=True)
                tracker.transition(task, task.kill)
                self._concurrency.release_inter_broker(brokers)
            else:
                still.append(task)
        in_flight[:] = still

    def _intra_broker_move_phase(self) -> bool:
        """Executor.intraBrokerMoveReplicas:1672: submit alterReplicaLogDirs
        batches under the per-broker intra-broker cap, poll replica logdir
        placement for completion, DEAD-mark moves whose broker died or that
        timed out. Backends without a JBOD surface fail queued intra tasks
        instead of silently completing them."""
        assert self._planner is not None and self._task_manager is not None
        self._set_phase(ExecutorState.INTRA_BROKER_REPLICA_MOVEMENT_TASK_IN_PROGRESS)
        tracker = self._task_manager.tracker

        alter = getattr(self._admin, "alter_replica_logdirs", None)
        lookup = getattr(self._admin, "replica_logdirs", None)
        if alter is None or lookup is None:
            # Not JBOD-capable: every queued logdir move is DEAD on arrival
            # (the reference would get an ApiException per task).
            for task in self._planner.intra_broker_tasks(max_total=1 << 30):
                tracker.transition(task, task.in_progress)
                tracker.transition(task, task.kill)
            return not self._stop_requested.is_set()

        in_flight: list[ExecutionTask] = []
        while True:
            if self._stop_requested.is_set():
                # Pending tasks abort; in-flight logdir copies cannot be
                # cancelled through the admin API — mark them aborted and
                # let the broker finish or fail them.
                dropped = self._planner.intra_broker_tasks(max_total=1 << 30)
                for task in dropped + in_flight:
                    if task.state is TaskState.PENDING:
                        tracker.transition(task, task.in_progress)
                    tracker.transition(task, task.abort)
                    tracker.transition(task, task.aborted)
                in_flight.clear()
                return False

            inflight_per_broker: dict[int, int] = {}
            for t in in_flight:
                b = t.proposal.logdir_broker
                inflight_per_broker[b] = inflight_per_broker.get(b, 0) + 1
            batch = self._planner.intra_broker_tasks(
                max_total=self._concurrency.cluster_intra_broker_headroom(
                    len(in_flight)),
                per_broker_cap=self._concurrency.intra_broker_per_broker_cap(),
                in_flight_per_broker=inflight_per_broker)
            if batch:
                from ..utils.tracing import TRACER
                with TRACER.span("executor.batch_submit",
                                 type="INTRA_BROKER_REPLICA_ACTION",
                                 tasks=len(batch)) as sp:
                    moves = [(t.topic_partition, t.proposal.logdir_broker,
                              t.proposal.destination_logdir) for t in batch]
                    rejected: set = set()
                    ok = self._submit_batch(
                        "admin.alter_replica_logdirs", batch,
                        lambda: rejected.update(alter(moves) or ()))
                    if not ok:
                        sp.set(submit_failed=True)
                        batch = []
                    if batch:
                        finished, total = tracker.progress()
                        self._heal.phase(
                            "execution_progress",
                            type="intra_broker", submitted=len(batch),
                            finished=finished, total=total)
                    for task in batch:
                        tracker.transition(task, task.in_progress)
                        p = task.proposal
                        if (p.topic, p.partition, p.logdir_broker) \
                                in rejected:
                            # Broker refused the move (bad/dead destination
                            # dir): DEAD immediately, don't poll a move
                            # that will never happen.
                            tracker.transition(task, task.kill)
                        else:
                            in_flight.append(task)

            if not in_flight and self._planner.num_pending(
                    TaskType.INTRA_BROKER_REPLICA_ACTION) == 0:
                return True

            time.sleep(self._poll_interval)
            self._poll_intra_broker(in_flight, lookup)

    def _poll_intra_broker(self, in_flight: list[ExecutionTask],
                           lookup) -> None:
        """Completion = the replica's current logdir equals the destination
        (DescribeLogDirs polling, ExecutorAdminUtils semantics); DEAD when
        the broker died or the task timed out."""
        assert self._task_manager is not None
        tracker = self._task_manager.tracker
        # Restrict the DescribeLogDirs fan-out to brokers with in-flight
        # moves (ExecutorAdminUtils.getLogdirInfoForExecutingReplicaMove).
        def fetch_dirs():
            try:
                return lookup(sorted({t.proposal.logdir_broker
                                      for t in in_flight}))
            except TypeError:
                return lookup()

        try:
            dirs = self._admin_call("admin.replica_logdirs", fetch_dirs)
            alive = self._admin_call("admin.alive_brokers",
                                     self._admin.alive_brokers)
        except Exception:  # noqa: BLE001 — degrade: skip this poll round
            from ..utils.sensors import SENSORS
            SENSORS.count("executor_poll_failures")
            import logging
            logging.getLogger(__name__).warning(
                "executor logdir poll failed; will retry next interval",
                exc_info=True)
            return
        now = time.time()
        still: list[ExecutionTask] = []
        for task in in_flight:
            p = task.proposal
            key = (p.topic, p.partition, p.logdir_broker)
            if dirs.get(key) == p.destination_logdir:
                tracker.transition(task, task.completed)
            elif p.logdir_broker not in alive \
                    or self._task_timed_out(task, now):
                tracker.transition(task, task.kill)
            else:
                still.append(task)
        in_flight[:] = still

    def _leadership_phase(self) -> bool:
        """Executor.moveLeaderships:1732 → electLeaders batches."""
        assert self._planner is not None and self._task_manager is not None
        self._set_phase(ExecutorState.LEADER_MOVEMENT_TASK_IN_PROGRESS)
        tracker = self._task_manager.tracker
        while True:
            if self._stop_requested.is_set():
                for task in self._planner.leadership_tasks(max_total=1 << 30):
                    tracker.transition(task, task.in_progress)
                    tracker.transition(task, task.abort)
                    tracker.transition(task, task.aborted)
                return False
            batch = self._planner.leadership_tasks(
                self._concurrency.leadership_cap(),
                per_broker_cap=self._concurrency.leadership_per_broker_cap())
            if not batch:
                return True
            from ..utils.tracing import TRACER
            failed = False
            with TRACER.span("executor.batch_submit",
                             type="LEADER_ACTION", tasks=len(batch)) as sp:
                if not self._submit_batch(
                        "admin.elect_leaders", batch,
                        lambda: self._admin.elect_leaders(
                            [t.topic_partition for t in batch])):
                    sp.set(submit_failed=True)
                    failed = True
                else:
                    try:
                        parts = self._admin_call(
                            "admin.describe_partitions",
                            self._admin.describe_partitions)
                    except Exception:  # noqa: BLE001 — the election
                        # landed; only the completion READ-BACK failed
                        # past retries. A verify failure, not a
                        # submission failure: requeue on the verify
                        # budget (idempotent re-election), never
                        # dead-letter.
                        from ..utils.sensors import SENSORS
                        SENSORS.count("executor_poll_failures")
                        self._requeue_or_kill_unverified(batch)
                        failed = True
                    else:
                        missing: list[ExecutionTask] = []
                        for task in batch:
                            p = parts.get(task.topic_partition)
                            if p is None:
                                # Absent from a (possibly PARTIAL/
                                # degraded) metadata read: unknown is
                                # not failed — re-verify.
                                missing.append(task)
                                continue
                            tracker.transition(task, task.in_progress)
                            if p.leader == task.proposal.new_leader:
                                tracker.transition(task, task.completed)
                            else:
                                tracker.transition(task, task.kill)
                        if missing:
                            self._requeue_or_kill_unverified(missing)
                        finished, total = tracker.progress()
                        self._heal.phase(
                            "execution_progress",
                            type="leadership", submitted=len(batch),
                            finished=finished, total=total)
            if failed:
                # Outside the span: idle backoff must not inflate the
                # recorded batch_submit duration.
                time.sleep(self._poll_interval)
                continue
            time.sleep(0)  # yield between batches
