"""Execution task state machine and bookkeeping.

Reference parity: executor/ExecutionTask.java (305 LoC; state machine
PENDING → IN_PROGRESS → ABORTING/ABORTED/DEAD/COMPLETED),
executor/ExecutionTaskTracker.java (433), executor/ExecutionTaskManager.java
(384). The task types mirror ExecutionTask.TaskType: INTER_BROKER_REPLICA_ACTION,
INTRA_BROKER_REPLICA_ACTION, LEADER_ACTION.
"""

from __future__ import annotations

import dataclasses
import enum
import itertools
import threading
import time
from typing import Iterable

from ..analyzer.proposals import ExecutionProposal


class TaskType(enum.Enum):
    INTER_BROKER_REPLICA_ACTION = "inter_broker_replica_action"
    INTRA_BROKER_REPLICA_ACTION = "intra_broker_replica_action"
    LEADER_ACTION = "leader_action"


class TaskState(enum.Enum):
    PENDING = "pending"
    IN_PROGRESS = "in_progress"
    ABORTING = "aborting"
    ABORTED = "aborted"
    DEAD = "dead"
    COMPLETED = "completed"
    # EXECUTION_ABANDONED (resilience layer, round 9): the dead-letter
    # terminal state — submission kept failing transiently past the
    # retry budget, so the task is parked instead of hanging the whole
    # execution until the global timeout. Distinct from DEAD (the
    # cluster rejected/lost the work) so operators can tell "broker
    # refused" from "control plane never got through".
    ABANDONED = "abandoned"


# Legal transitions (ExecutionTask.java VALID_TRANSFER map; ABANDONED is
# reached from PENDING — the task was never successfully submitted).
_VALID = {
    TaskState.PENDING: {TaskState.IN_PROGRESS, TaskState.ABANDONED},
    TaskState.IN_PROGRESS: {TaskState.ABORTING, TaskState.DEAD,
                            TaskState.COMPLETED},
    TaskState.ABORTING: {TaskState.ABORTED, TaskState.DEAD},
    TaskState.ABORTED: set(),
    TaskState.DEAD: set(),
    TaskState.COMPLETED: set(),
    TaskState.ABANDONED: set(),
}


@dataclasses.dataclass
class ExecutionTask:
    """One unit of executed work for a partition (ExecutionTask.java)."""

    execution_id: int
    proposal: ExecutionProposal
    task_type: TaskType
    state: TaskState = TaskState.PENDING
    start_time_ms: int = -1
    end_time_ms: int = -1
    alert_time_ms: int = -1

    def _transfer(self, to: TaskState) -> None:
        if to not in _VALID[self.state]:
            raise ValueError(
                f"illegal task state transfer {self.state.value} -> {to.value} "
                f"for task {self.execution_id}")
        self.state = to

    def in_progress(self, now_ms: int | None = None) -> None:
        self._transfer(TaskState.IN_PROGRESS)
        self.start_time_ms = now_ms if now_ms is not None else _now_ms()

    def completed(self, now_ms: int | None = None) -> None:
        self._transfer(TaskState.COMPLETED)
        self.end_time_ms = now_ms if now_ms is not None else _now_ms()

    def kill(self, now_ms: int | None = None) -> None:
        self._transfer(TaskState.DEAD)
        self.end_time_ms = now_ms if now_ms is not None else _now_ms()

    def abort(self) -> None:
        self._transfer(TaskState.ABORTING)

    def abandon(self, now_ms: int | None = None) -> None:
        self._transfer(TaskState.ABANDONED)
        self.end_time_ms = now_ms if now_ms is not None else _now_ms()

    def aborted(self, now_ms: int | None = None) -> None:
        self._transfer(TaskState.ABORTED)
        self.end_time_ms = now_ms if now_ms is not None else _now_ms()

    @property
    def topic_partition(self) -> tuple[str, int]:
        return (self.proposal.topic, self.proposal.partition)

    def brokers_to_add(self) -> tuple[int, ...]:
        return self.proposal.replicas_to_add

    def brokers_to_remove(self) -> tuple[int, ...]:
        return self.proposal.replicas_to_remove

    def to_dict(self) -> dict:
        return {
            "executionId": self.execution_id,
            "type": self.task_type.value,
            "state": self.state.value,
            "proposal": {
                "topicPartition": f"{self.proposal.topic}-{self.proposal.partition}",
                "oldLeader": self.proposal.old_leader,
                "oldReplicas": list(self.proposal.old_replicas),
                "newReplicas": list(self.proposal.new_replicas),
                "newLeader": self.proposal.new_leader,
            },
        }


def _now_ms() -> int:
    return int(time.time() * 1000)


class ExecutionTaskTracker:
    """Task counts by (type, state) + recent history
    (ExecutionTaskTracker.java)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._tasks: dict[TaskType, dict[TaskState, set[int]]] = {
            t: {s: set() for s in TaskState} for t in TaskType}
        self._by_id: dict[int, ExecutionTask] = {}

    def add(self, tasks: Iterable[ExecutionTask]) -> None:
        with self._lock:
            for t in tasks:
                self._tasks[t.task_type][t.state].add(t.execution_id)
                self._by_id[t.execution_id] = t

    def transition(self, task: ExecutionTask, action) -> None:
        """Apply ``action`` (a bound state-machine method) and reindex."""
        with self._lock:
            self._tasks[task.task_type][task.state].discard(task.execution_id)
            action()
            self._tasks[task.task_type][task.state].add(task.execution_id)

    def counts(self) -> dict[str, dict[str, int]]:
        with self._lock:
            return {t.value: {s.value: len(ids) for s, ids in by_state.items() if ids}
                    for t, by_state in self._tasks.items()}

    def tasks_in(self, task_type: TaskType, *states: TaskState) -> list[ExecutionTask]:
        with self._lock:
            ids = set().union(*(self._tasks[task_type][s] for s in states))
            return [self._by_id[i] for i in sorted(ids)]

    def num_finished(self) -> int:
        done = (TaskState.COMPLETED, TaskState.ABORTED, TaskState.DEAD,
                TaskState.ABANDONED)
        with self._lock:
            return sum(len(self._tasks[t][s]) for t in TaskType for s in done)

    def progress(self) -> tuple[int, int]:
        """(finished, total) under one lock acquisition — the heal
        ledger's per-batch movement-progress snapshot."""
        done = (TaskState.COMPLETED, TaskState.ABORTED, TaskState.DEAD,
                TaskState.ABANDONED)
        with self._lock:
            finished = sum(len(self._tasks[t][s])
                           for t in TaskType for s in done)
            return finished, len(self._by_id)

    def num_total(self) -> int:
        with self._lock:
            return len(self._by_id)

    def is_done(self) -> bool:
        return self.num_finished() == self.num_total()


class ExecutionTaskManager:
    """Creates tasks from proposals and owns the tracker
    (ExecutionTaskManager.java). Phases (ExecutionTaskPlanner semantics):
    a proposal can expand into up to three tasks — inter-broker move,
    intra-broker move (logdir, not yet modeled), and a leader action when
    the leader changes or the old leader is removed."""

    def __init__(self):
        self._id_gen = itertools.count()
        self.tracker = ExecutionTaskTracker()

    def tasks_from_proposals(self, proposals: Iterable[ExecutionProposal],
                             ) -> list[ExecutionTask]:
        tasks: list[ExecutionTask] = []
        for p in proposals:
            # Order-sensitive: a leadership-only proposal still needs a
            # (metadata-only) reassignment to reorder the replica list,
            # because preferred-leader election picks replicas[0]
            # (ExecutionProposal leader-first convention).
            if tuple(p.old_replicas) != tuple(p.new_replicas):
                tasks.append(ExecutionTask(next(self._id_gen), p,
                                           TaskType.INTER_BROKER_REPLICA_ACTION))
            if p.has_logdir_move:
                tasks.append(ExecutionTask(next(self._id_gen), p,
                                           TaskType.INTRA_BROKER_REPLICA_ACTION))
            if p.new_leader != p.old_leader and p.new_leader >= 0:
                tasks.append(ExecutionTask(next(self._id_gen), p,
                                           TaskType.LEADER_ACTION))
        self.tracker.add(tasks)
        return tasks
