"""ExecutorNotifier SPI: alert on execution finish/stop.

Reference parity: executor/ExecutorNotifier.java (SPI; sendNotification on
execution finished or user-stopped) + the noop implementation. The notifier
receives the execution summary record the executor also appends to its
history, so external systems (ticketing, chat-ops) can mirror the
operation audit log.
"""

from __future__ import annotations

import logging
from typing import Protocol

LOG = logging.getLogger(__name__)


class ExecutorNotifier(Protocol):
    def on_execution_finished(self, summary: dict) -> None: ...

    def on_execution_stopped(self, summary: dict) -> None: ...

    # Resilience events (round 9). Optional for custom notifiers: the
    # executor dispatches them via getattr, so an implementation
    # predating the protocol extension keeps working.
    def on_task_timeout(self, task: dict) -> None: ...

    def on_tasks_abandoned(self, summary: dict) -> None: ...


class NoopExecutorNotifier:
    def on_execution_finished(self, summary: dict) -> None:
        pass

    def on_execution_stopped(self, summary: dict) -> None:
        pass

    def on_task_timeout(self, task: dict) -> None:
        pass

    def on_tasks_abandoned(self, summary: dict) -> None:
        pass


class LoggingExecutorNotifier:
    """Default: mirrors the reference's OPERATION_LOGGER-style audit line."""

    def on_execution_finished(self, summary: dict) -> None:
        LOG.info("execution finished: %s", summary)

    def on_execution_stopped(self, summary: dict) -> None:
        LOG.warning("execution stopped: %s", summary)

    def on_task_timeout(self, task: dict) -> None:
        LOG.warning("execution task timed out: %s", task)

    def on_tasks_abandoned(self, summary: dict) -> None:
        LOG.error("execution tasks dead-lettered (submission kept "
                  "failing): %s", summary)


class WebhookExecutorNotifier:
    """POST the summary as JSON to a webhook (injectable http_post for
    tests; shares the detector notifiers' webhook helper)."""

    def __init__(self, url: str, http_post=None):
        from ..detector.notifier import _default_http_post

        self._url = url
        self._post = http_post or _default_http_post

    def on_execution_finished(self, summary: dict) -> None:
        self._post(self._url, {"event": "execution_finished", **summary})

    def on_execution_stopped(self, summary: dict) -> None:
        self._post(self._url, {"event": "execution_stopped", **summary})

    def on_task_timeout(self, task: dict) -> None:
        self._post(self._url, {"event": "task_timeout", **task})

    def on_tasks_abandoned(self, summary: dict) -> None:
        self._post(self._url, {"event": "tasks_abandoned", **summary})
