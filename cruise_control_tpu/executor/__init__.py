"""Executor layer: applies optimization proposals to the managed cluster.

Reference parity: executor/ (7,370 LoC — Executor, ExecutionTaskPlanner,
ExecutionTask state machine, concurrency manager + adjuster, movement
strategies, replication throttling, admin glue). The admin boundary is
pluggable; an in-memory fake backs tests and simulations.
"""

from .admin import AdminBackend, InMemoryAdminBackend, PartitionState
from .concurrency import ConcurrencyCaps, ExecutionConcurrencyManager
from .executor import (
    Executor, ExecutorState, OngoingExecutionError,
    OngoingExternalReassignmentError,
)
from .notifier import (
    ExecutorNotifier, LoggingExecutorNotifier, NoopExecutorNotifier,
    WebhookExecutorNotifier,
)
from .planner import ExecutionTaskPlanner
from .strategy import (
    BaseReplicaMovementStrategy, PostponeUrpReplicaMovementStrategy,
    PrioritizeLargeReplicaMovementStrategy, PrioritizeMinIsrWithOfflineReplicasStrategy,
    PrioritizeSmallReplicaMovementStrategy, ReplicaMovementStrategy,
    STRATEGIES, strategy_chain,
)
from .task import (
    ExecutionTask, ExecutionTaskManager, ExecutionTaskTracker, TaskState, TaskType,
)
from .throttle import ReplicationThrottleHelper

__all__ = [
    "AdminBackend", "InMemoryAdminBackend", "PartitionState",
    "ConcurrencyCaps", "ExecutionConcurrencyManager",
    "Executor", "ExecutorState", "OngoingExecutionError",
    "OngoingExternalReassignmentError", "ExecutorNotifier",
    "LoggingExecutorNotifier", "NoopExecutorNotifier",
    "WebhookExecutorNotifier",
    "ExecutionTaskPlanner",
    "BaseReplicaMovementStrategy", "PostponeUrpReplicaMovementStrategy",
    "PrioritizeLargeReplicaMovementStrategy",
    "PrioritizeMinIsrWithOfflineReplicasStrategy",
    "PrioritizeSmallReplicaMovementStrategy", "ReplicaMovementStrategy",
    "STRATEGIES", "strategy_chain",
    "ExecutionTask", "ExecutionTaskManager", "ExecutionTaskTracker",
    "TaskState", "TaskType", "ReplicationThrottleHelper",
]
