"""Cluster-admin backend: the executor's boundary to the managed Kafka
cluster.

Reference parity: executor/ExecutionUtils.java (750; submits+interprets
AdminClient calls — alterPartitionReassignments:483, electLeaders:433,
listPartitionsBeingReassigned) and ExecutorAdminUtils.java. The backend is
pluggable (SURVEY.md §4: "a fake Kafka admin/metadata backend for executor
logic"): ``InMemoryAdminBackend`` simulates reassignment progress for tests
and simulations; the wire binding (kafka.admin.KafkaAdminBackend) implements the same
protocol against a live cluster (gated: no Kafka client in this image).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Iterable, Mapping, Protocol, Sequence


@dataclasses.dataclass(frozen=True)
class PartitionState:
    topic: str
    partition: int
    replicas: tuple[int, ...]      # current assignment, leader first
    leader: int
    isr: tuple[int, ...]
    adding: tuple[int, ...] = ()   # reassignment in progress
    removing: tuple[int, ...] = ()

    @property
    def is_reassigning(self) -> bool:
        return bool(self.adding or self.removing)


class AdminBackend(Protocol):
    """Protocol over the handful of AdminClient calls the executor needs."""

    def alter_partition_reassignments(
            self, targets: Mapping[tuple[str, int], tuple[int, ...]]) -> None: ...

    def cancel_partition_reassignments(
            self, partitions: Iterable[tuple[str, int]]) -> None: ...

    def elect_leaders(self, partitions: Iterable[tuple[str, int]]) -> None: ...

    def list_reassigning_partitions(self) -> list[tuple[str, int]]: ...

    def describe_partitions(self) -> dict[tuple[str, int], PartitionState]: ...

    def alive_brokers(self) -> set[int]: ...

    def alter_broker_configs(self, configs: Mapping[int, Mapping[str, str]]) -> None: ...

    def alter_topic_configs(self, configs: Mapping[str, Mapping[str, str]]) -> None: ...

    def describe_broker_configs(self, brokers: Iterable[int]
                                ) -> dict[int, dict[str, str]]: ...

    def describe_topic_configs(self, topics: Iterable[str]
                               ) -> dict[str, dict[str, str]]: ...


class InMemoryAdminBackend:
    """Deterministic fake cluster: each ``tick()`` advances every ongoing
    reassignment by ``steps_per_tick`` replicas (new replicas join the ISR,
    removed ones leave), letting executor tests simulate slow/fast clusters,
    broker death mid-move, and external reassignments."""

    def __init__(self, partitions: Iterable[PartitionState],
                 steps_per_tick: int = 1_000_000,
                 auto_advance: bool = True,
                 dir_moves_per_tick: int = 1_000_000):
        self._lock = threading.RLock()
        self._parts: dict[tuple[str, int], PartitionState] = {
            (p.topic, p.partition): p for p in partitions}
        self._alive: set[int] = {b for p in self._parts.values() for b in p.replicas}
        # Metadata generation: bumped on every STRUCTURAL topology change
        # (replica sets, broker liveness) — NOT on leader-only elections,
        # which the model pipeline re-derives every refresh. The
        # LoadMonitor's incremental pipeline keys its topology cache on
        # this: an unchanged generation means the device-resident topology
        # tensors can be reused without any re-derivation or transfer.
        self._meta_gen = 0
        self._steps_per_tick = steps_per_tick
        self._dir_moves_per_tick = dir_moves_per_tick
        self._pending_dir_moves: dict[tuple[str, int, int], str] = {}
        # auto_advance: progress the simulated cluster whenever the executor
        # polls it, so tests don't need a separate ticking thread.
        self._auto_advance = auto_advance
        self.broker_configs: dict[int, dict[str, str]] = {}
        self.topic_configs: dict[str, dict[str, str]] = {}
        self.reassignment_calls = 0
        self.election_calls = 0

    def metadata_generation(self) -> int:
        """O(1) topology-change stamp (see __init__). Pure read — it must
        never tick the simulation itself."""
        with self._lock:
            return self._meta_gen

    # ---- test controls ----------------------------------------------------
    def kill_broker(self, broker: int) -> None:
        with self._lock:
            self._alive.discard(broker)
            self._meta_gen += 1

    def revive_broker(self, broker: int) -> None:
        with self._lock:
            self._alive.add(broker)
            self._meta_gen += 1

    def create_topic(self, topic: str, num_partitions: int, rf: int = 2,
                     brokers: Sequence[int] | None = None) -> None:
        """Topic-churn control (digital-twin simulator): add a topic with
        ``num_partitions`` partitions spread round-robin over the alive
        brokers (or an explicit ``brokers`` list). Structural change →
        metadata generation bump."""
        with self._lock:
            pool = sorted(self._alive) if brokers is None else list(brokers)
            if not pool:
                raise ValueError("create_topic: no alive brokers")
            eff_rf = min(rf, len(pool))
            for p in range(num_partitions):
                reps = tuple(pool[(p + k) % len(pool)] for k in range(eff_rf))
                self._parts[(topic, p)] = PartitionState(
                    topic, p, reps, reps[0], isr=reps)
                if hasattr(self, "_logdirs"):
                    for i, b in enumerate(reps):
                        dirs = sorted(self._logdirs.get(b, {}))
                        if dirs:
                            self._replica_dirs[(topic, p, b)] = \
                                dirs[(p + i) % len(dirs)]
            self._meta_gen += 1

    def delete_topic(self, topic: str) -> int:
        """Topic-churn control: drop every partition of ``topic`` (and its
        pending dir moves / dir placements). Returns partitions removed."""
        with self._lock:
            keys = [k for k in self._parts if k[0] == topic]
            for k in keys:
                del self._parts[k]
            for store in (self._pending_dir_moves,
                          getattr(self, "_replica_dirs", {})):
                for k in [k for k in store if k[0] == topic]:
                    del store[k]
            if keys:
                self._meta_gen += 1
            return len(keys)

    def expand_partitions(self, topic: str, new_count: int) -> int:
        """Topic-churn control: grow ``topic`` to ``new_count`` partitions
        (Kafka partition expansion — existing partitions untouched, new
        ones placed round-robin on alive brokers at the topic's RF).
        Returns the number of partitions added."""
        with self._lock:
            existing = sorted(p for (t, p) in self._parts if t == topic)
            if not existing:
                raise ValueError(f"expand_partitions: unknown topic {topic!r}")
            rf = len(self._parts[(topic, existing[0])].replicas)
            pool = sorted(self._alive)
            added = 0
            for p in range(existing[-1] + 1, new_count):
                reps = tuple(pool[(p + k) % len(pool)]
                             for k in range(min(rf, len(pool))))
                self._parts[(topic, p)] = PartitionState(
                    topic, p, reps, reps[0], isr=reps)
                if hasattr(self, "_logdirs"):
                    # Same placement rule as create_topic: expanded
                    # partitions must be visible to disk-health checks
                    # and intra-broker moves on JBOD clusters.
                    for i, b in enumerate(reps):
                        dirs = sorted(self._logdirs.get(b, {}))
                        if dirs:
                            self._replica_dirs[(topic, p, b)] = \
                                dirs[(p + i) % len(dirs)]
                added += 1
            if added:
                self._meta_gen += 1
            return added

    def tick(self) -> None:
        """Advance the simulated cluster one progress interval."""
        with self._lock:
            # In-flight logdir moves complete dir_moves_per_tick at a time
            # (brokers copy data between dirs; not instantaneous). Moves on
            # dead brokers stall.
            dir_budget = self._dir_moves_per_tick
            for key in sorted(self._pending_dir_moves):
                if dir_budget <= 0:
                    break
                _t, _p, broker = key
                if broker not in self._alive:
                    continue
                self._replica_dirs[key] = self._pending_dir_moves.pop(key)
                dir_budget -= 1
            budget = self._steps_per_tick
            for key in sorted(self._parts):
                if budget <= 0:
                    break
                p = self._parts[key]
                if not p.is_reassigning:
                    continue
                # New replicas catch up only if their broker is alive.
                adding = tuple(b for b in p.adding if b not in self._alive) \
                    if any(b not in self._alive for b in p.adding) else ()
                target = tuple(b for b in p.replicas if b not in p.removing)
                if adding:
                    # stalled: dead destination keeps the reassignment open
                    continue
                leader = p.leader if p.leader in target and p.leader in self._alive \
                    else next((b for b in target if b in self._alive), -1)
                self._parts[key] = PartitionState(
                    topic=p.topic, partition=p.partition, replicas=target,
                    leader=leader, isr=tuple(b for b in target if b in self._alive))
                self._meta_gen += 1
                budget -= 1

    # ---- AdminBackend protocol -------------------------------------------
    def alter_partition_reassignments(self, targets) -> None:
        with self._lock:
            self.reassignment_calls += 1
            for (topic, part), new_replicas in targets.items():
                p = self._parts[(topic, part)]
                adding = tuple(b for b in new_replicas if b not in p.replicas)
                removing = tuple(b for b in p.replicas if b not in new_replicas)
                merged = tuple(new_replicas) + removing
                leader = p.leader if p.leader in merged else new_replicas[0]
                self._parts[(topic, part)] = PartitionState(
                    topic=topic, partition=part, replicas=merged, leader=leader,
                    isr=tuple(b for b in merged if b in self._alive),
                    adding=adding, removing=removing)
                self._meta_gen += 1

    def cancel_partition_reassignments(self, partitions) -> None:
        with self._lock:
            for key in partitions:
                p = self._parts.get(key)
                if p is None or not p.is_reassigning:
                    continue
                original = tuple(b for b in p.replicas if b not in p.adding)
                self._parts[key] = PartitionState(
                    topic=p.topic, partition=p.partition, replicas=original,
                    leader=p.leader if p.leader in original else original[0],
                    isr=tuple(b for b in original if b in self._alive))
                self._meta_gen += 1

    def elect_leaders(self, partitions) -> None:
        with self._lock:
            self.election_calls += 1
            for key in partitions:
                p = self._parts[key]
                preferred = p.replicas[0] if p.replicas else -1
                if preferred in self._alive and preferred in p.isr:
                    # Leader-only change: deliberately NOT a metadata
                    # generation bump — the model pipeline re-derives
                    # leadership from the live partition states on every
                    # refresh, so elections stay on the cheap path.
                    self._parts[key] = dataclasses.replace(p, leader=preferred)

    def list_reassigning_partitions(self):
        with self._lock:
            return [k for k, p in self._parts.items() if p.is_reassigning]

    def describe_partitions(self):
        with self._lock:
            if self._auto_advance:
                self.tick()
            return dict(self._parts)

    def alive_brokers(self):
        with self._lock:
            return set(self._alive)

    def alter_broker_configs(self, configs) -> None:
        # Incremental-alter semantics: value None deletes the key
        # (AlterConfigOp.OpType.DELETE), anything else sets it.
        with self._lock:
            for broker, kv in configs.items():
                target = self.broker_configs.setdefault(broker, {})
                for k, v in kv.items():
                    if v is None:
                        target.pop(k, None)
                    else:
                        target[k] = v

    def alter_topic_configs(self, configs) -> None:
        with self._lock:
            for topic, kv in configs.items():
                target = self.topic_configs.setdefault(topic, {})
                for k, v in kv.items():
                    if v is None:
                        target.pop(k, None)
                    else:
                        target[k] = v

    def describe_broker_configs(self, brokers):
        with self._lock:
            return {b: dict(self.broker_configs.get(b, {})) for b in brokers}

    def describe_topic_configs(self, topics):
        with self._lock:
            return {t: dict(self.topic_configs.get(t, {})) for t in topics}

    # ---- JBOD (log-dir) surface ------------------------------------------
    def enable_jbod(self, logdirs_by_broker: Mapping[int, Sequence[str]],
                    placement: Mapping[tuple[str, int, int], str] | None = None,
                    ) -> None:
        """Give brokers named log dirs; replicas without an explicit
        placement land round-robin (tests / demo)."""
        with self._lock:
            self._logdirs = {b: {d: True for d in dirs}
                             for b, dirs in logdirs_by_broker.items()}
            self._replica_dirs = dict(placement or {})
            for (topic, part), p in sorted(self._parts.items()):
                for i, b in enumerate(p.replicas):
                    key = (topic, part, b)
                    dirs = sorted(self._logdirs.get(b, {}))
                    if key not in self._replica_dirs and dirs:
                        self._replica_dirs[key] = dirs[(part + i) % len(dirs)]

    def kill_logdir(self, broker: int, logdir: str) -> None:
        with self._lock:
            self._logdirs[broker][logdir] = False

    def describe_logdirs(self) -> dict[int, dict[str, bool]]:
        with self._lock:
            if not hasattr(self, "_logdirs"):
                return {}
            return {b: dict(d) for b, d in self._logdirs.items()}

    def replica_logdirs(self, brokers: Iterable[int] | None = None,
                        ) -> dict[tuple[str, int, int], str]:
        if self._auto_advance:
            self.tick()
        with self._lock:
            dirs = dict(getattr(self, "_replica_dirs", {}))
        if brokers is not None:
            wanted = set(brokers)
            dirs = {k: v for k, v in dirs.items() if k[2] in wanted}
        return dirs

    def alter_replica_logdirs(self, moves: Sequence[tuple[tuple[str, int], int, str]],
                              ) -> list[tuple[str, int, int]]:
        """(topic-partition, broker, destination dir) — queued; ``tick()``
        completes up to ``dir_moves_per_tick`` of them (the real
        alterReplicaLogDirs returns immediately and the broker copies data
        in the background; completion is observed via DescribeLogDirs).
        Returns the keys rejected outright (destination dir unknown/dead —
        the per-partition error codes of the real API)."""
        failed: list[tuple[str, int, int]] = []
        with self._lock:
            if not hasattr(self, "_replica_dirs"):
                self._replica_dirs = {}
            for (topic, part), broker, dst in moves:
                known = getattr(self, "_logdirs", {}).get(broker)
                if known is not None and not known.get(dst, False):
                    failed.append((topic, part, broker))
                    continue
                if self._replica_dirs.get((topic, part, broker)) != dst:
                    self._pending_dir_moves[(topic, part, broker)] = dst
        return failed

    # ---- ClusterInfo protocol for strategies ------------------------------
    def partition_size(self, topic: str, partition: int) -> float:
        return 1.0

    def is_under_replicated(self, topic: str, partition: int) -> bool:
        with self._lock:
            p = self._parts[(topic, partition)]
            return len(p.isr) < len(p.replicas)

    def is_under_min_isr_with_offline(self, topic: str, partition: int) -> bool:
        with self._lock:
            p = self._parts[(topic, partition)]
            # ccsa: ok[CCSA005] KAFKA topic-config key space (broker-side
            # TopicConfig), not a cruise-control config key
            raw = self.topic_configs.get(topic, {}).get(
                "min.insync.replicas", "1")
            live = [b for b in p.isr if b in self._alive]
            return len(live) < int(raw) \
                and any(b not in self._alive for b in p.replicas)
