"""Replication throttling during reassignments.

Reference parity: executor/ReplicationThrottleHelper.java (451 LoC): before
submitting inter-broker moves, set ``leader.replication.throttled.rate`` /
``follower.replication.throttled.rate`` on the participating brokers and
``leader.replication.throttled.replicas`` / ``follower...`` on the moved
topics; clear them when the affected tasks finish (only the values this
helper set — user-set throttles are preserved).
"""

from __future__ import annotations

from typing import Iterable

from .admin import AdminBackend
from .task import ExecutionTask

_KEEP = object()  # sentinel: begin_execution() keeps the configured rate

LEADER_RATE = "leader.replication.throttled.rate"
FOLLOWER_RATE = "follower.replication.throttled.rate"
LEADER_REPLICAS = "leader.replication.throttled.replicas"
FOLLOWER_REPLICAS = "follower.replication.throttled.replicas"
WILDCARD = "*"


class ReplicationThrottleHelper:
    def __init__(self, admin: AdminBackend, rate_bytes_per_sec: int | None):
        self._admin = admin
        self._rate = rate_bytes_per_sec
        self._default_rate = rate_bytes_per_sec
        # Brokers excluded from throttling for the CURRENT execution
        # (throttle_added_broker/throttle_removed_broker=false:
        # ReplicationThrottleHelper.java applies rates only to brokers the
        # caller asks to throttle).
        self._excluded_brokers: set[int] = set()
        # broker/topic -> {key: previous value} so operator-set throttles are
        # restored on clear (ReplicationThrottleHelper.java checks existing
        # configs before removing). None marks a key that did not exist;
        # clear passes it through as a config DELETE.
        self._saved_broker: dict[int, dict[str, str | None]] = {}
        self._saved_topic: dict[str, dict[str, str | None]] = {}

    def begin_execution(self, rate_override: int | None = _KEEP,
                        excluded_brokers: Iterable[int] = ()) -> None:
        """Per-execution settings (cleared by ``clear_throttles``): a
        replication_throttle request-param override of the configured rate,
        and brokers to leave unthrottled
        (throttle_added_broker/throttle_removed_broker=false)."""
        if rate_override is not _KEEP:
            self._rate = rate_override
        self._excluded_brokers = set(excluded_brokers)

    def set_throttles(self, tasks: Iterable[ExecutionTask]) -> None:
        if self._rate is None:
            return
        brokers: set[int] = set()
        topics: set[str] = set()
        for t in tasks:
            brokers |= set(t.proposal.old_replicas) | set(t.proposal.new_replicas)
            topics.add(t.proposal.topic)
        brokers -= self._excluded_brokers
        new_brokers = brokers - self._saved_broker.keys()
        if new_brokers:
            existing = self._admin.describe_broker_configs(new_brokers)
            for b in new_brokers:
                self._saved_broker[b] = {k: existing.get(b, {}).get(k)
                                         for k in (LEADER_RATE, FOLLOWER_RATE)}
            self._admin.alter_broker_configs({
                b: {LEADER_RATE: str(self._rate), FOLLOWER_RATE: str(self._rate)}
                for b in new_brokers})
        new_topics = topics - self._saved_topic.keys()
        if new_topics:
            existing_t = self._admin.describe_topic_configs(new_topics)
            for t in new_topics:
                self._saved_topic[t] = {k: existing_t.get(t, {}).get(k)
                                        for k in (LEADER_REPLICAS, FOLLOWER_REPLICAS)}
            self._admin.alter_topic_configs({
                t: {LEADER_REPLICAS: WILDCARD, FOLLOWER_REPLICAS: WILDCARD}
                for t in new_topics})

    def clear_throttles(self) -> None:
        if self._rate is not None:
            if self._saved_broker:
                self._admin.alter_broker_configs(dict(self._saved_broker))
                self._saved_broker.clear()
            if self._saved_topic:
                self._admin.alter_topic_configs(dict(self._saved_topic))
                self._saved_topic.clear()
        # Per-execution overrides do not outlive the execution.
        self._rate = self._default_rate
        self._excluded_brokers = set()
