"""Execution task planning: which tasks are ready to submit next.

Reference parity: executor/ExecutionTaskPlanner.java (540 LoC): pending
task pools per type; inter-broker tasks are dequeued only when BOTH the
source and destination brokers have concurrency headroom
(getInterBrokerReplicaMovementTasks(readyBrokers):348); ordering is the
pluggable ReplicaMovementStrategy chain.
"""

from __future__ import annotations

import threading
from typing import Iterable

from .strategy import ClusterInfo, ReplicaMovementStrategy, strategy_chain
from .task import ExecutionTask, TaskType


class ExecutionTaskPlanner:
    def __init__(self, strategy: ReplicaMovementStrategy | None = None):
        self._strategy = strategy or strategy_chain([])
        self._lock = threading.Lock()
        self._pending: dict[TaskType, list[ExecutionTask]] = {t: [] for t in TaskType}

    def add_tasks(self, tasks: Iterable[ExecutionTask], cluster: ClusterInfo) -> None:
        with self._lock:
            for t in tasks:
                self._pending[t.task_type].append(t)
            self._pending[TaskType.INTER_BROKER_REPLICA_ACTION] = self._strategy.sort(
                self._pending[TaskType.INTER_BROKER_REPLICA_ACTION], cluster)

    def num_pending(self, task_type: TaskType | None = None) -> int:
        with self._lock:
            if task_type is not None:
                return len(self._pending[task_type])
            return sum(len(v) for v in self._pending.values())

    def inter_broker_tasks(self, headroom_of, max_total: int) -> list[ExecutionTask]:
        """Dequeue inter-broker tasks whose participating brokers all have
        headroom; ``headroom_of(broker) -> int`` is consulted and decremented
        greedily in strategy order (ExecutionTaskPlanner.java:348)."""
        picked: list[ExecutionTask] = []
        budget: dict[int, int] = {}

        def room(b: int) -> int:
            if b not in budget:
                budget[b] = headroom_of(b)
            return budget[b]

        with self._lock:
            remaining = []
            for task in self._pending[TaskType.INTER_BROKER_REPLICA_ACTION]:
                if len(picked) >= max_total:
                    remaining.append(task)
                    continue
                brokers = set(task.proposal.replicas_to_add) \
                    | set(task.proposal.replicas_to_remove)
                # Reorder-only tasks (empty add/remove sets) are metadata
                # writes; they bypass per-broker movement caps.
                if all(room(b) > 0 for b in brokers):
                    for b in brokers:
                        budget[b] -= 1
                    picked.append(task)
                else:
                    remaining.append(task)
            self._pending[TaskType.INTER_BROKER_REPLICA_ACTION] = remaining
        return picked

    def leadership_tasks(self, max_total: int,
                         per_broker_cap: int | None = None) -> list[ExecutionTask]:
        """Dequeue leadership moves, bounding how many land on any single
        new-leader broker per batch (num.concurrent.leader.movements.per.broker)."""
        return self._capped_dequeue(TaskType.LEADER_ACTION, max_total,
                                    per_broker_cap,
                                    lambda t: (t.proposal.new_leader,))

    def intra_broker_tasks(self, max_total: int,
                           per_broker_cap: int | None = None,
                           in_flight_per_broker: dict[int, int] | None = None,
                           ) -> list[ExecutionTask]:
        """Dequeue intra-broker (logdir) moves, capped per affected broker
        (num.concurrent.intra.broker.partition.movements). The caller's
        in-flight counts seed the per-broker usage so the cap holds ACROSS
        poll intervals, not just within one batch."""
        return self._capped_dequeue(TaskType.INTRA_BROKER_REPLICA_ACTION,
                                    max_total, per_broker_cap,
                                    lambda t: (t.proposal.logdir_broker,),
                                    in_flight_per_broker)

    def _capped_dequeue(self, task_type: TaskType, max_total: int,
                        per_broker_cap: int | None,
                        brokers_of,
                        initial_used: dict[int, int] | None = None,
                        ) -> list[ExecutionTask]:
        with self._lock:
            picked: list[ExecutionTask] = []
            remaining: list[ExecutionTask] = []
            used: dict[int, int] = dict(initial_used or {})
            for task in self._pending[task_type]:
                brokers = brokers_of(task)
                fits = len(picked) < max_total and (
                    per_broker_cap is None
                    or all(used.get(b, 0) < per_broker_cap for b in brokers))
                if fits:
                    for b in brokers:
                        used[b] = used.get(b, 0) + 1
                    picked.append(task)
                else:
                    remaining.append(task)
            self._pending[task_type] = remaining
            return picked

    def clear(self) -> list[ExecutionTask]:
        """Drop all pending tasks (stop-execution); returns the dropped."""
        with self._lock:
            dropped = [t for pool in self._pending.values() for t in pool]
            for pool in self._pending.values():
                pool.clear()
            return dropped
