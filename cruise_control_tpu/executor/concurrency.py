"""Execution concurrency control.

Reference parity: executor/concurrency/ExecutionConcurrencyManager.java (355;
per-broker and cluster-wide caps for inter-broker, intra-broker and
leadership actions) and the ConcurrencyAdjuster inside Executor.java:465-683
(periodically raises/lowers caps from broker health: under-min-ISR state
halves throughput, healthy metrics step it up).
"""

from __future__ import annotations

import dataclasses
import threading


@dataclasses.dataclass
class ConcurrencyCaps:
    """Defaults follow config/cruisecontrol.properties
    (num.concurrent.partition.movements.per.broker=10,
    max.num.cluster.partition.movements=1250,
    num.concurrent.intra.broker.partition.movements=2,
    num.concurrent.leader.movements=1000)."""

    inter_broker_per_broker: int = 10
    cluster_inter_broker: int = 1250
    intra_broker_per_broker: int = 2
    leadership_cluster: int = 1000
    leadership_per_broker: int = 250


class ExecutionConcurrencyManager:
    """Tracks caps + in-flight counts; thread-safe
    (ExecutionConcurrencyManager.java)."""

    # Adjuster bounds (ConcurrencyAdjuster MIN/MAX constants).
    MIN_INTER_BROKER = 1
    MAX_INTER_BROKER_MULTIPLIER = 2
    MIN_LEADERSHIP = 100

    # ConcurrencyType names accepted by the ADMIN endpoint's
    # (en|dis)able_concurrency_adjuster_for toggles (ConcurrencyType.java).
    ADJUSTER_TYPES = ("INTER_BROKER_REPLICA", "INTRA_BROKER_REPLICA",
                      "LEADERSHIP")

    def __init__(self, caps: ConcurrencyCaps | None = None):
        self._caps = caps or ConcurrencyCaps()
        self._base = dataclasses.replace(self._caps)
        self._lock = threading.Lock()
        self._inter_in_flight: dict[int, int] = {}   # broker -> count
        self._cluster_inter_in_flight = 0
        self._adjuster_enabled = {t: True for t in self.ADJUSTER_TYPES}
        self._min_isr_based_adjustment = True

    # ---- capacity queries -------------------------------------------------
    def inter_broker_headroom(self, broker: int) -> int:
        with self._lock:
            per = self._caps.inter_broker_per_broker - self._inter_in_flight.get(broker, 0)
            cluster = self._caps.cluster_inter_broker - self._cluster_inter_in_flight
            return max(0, min(per, cluster))

    def cluster_inter_broker_headroom(self) -> int:
        """Remaining cluster-wide inter-broker movement capacity; batch
        sizes must be bounded by this, not the raw cap, or concurrent
        batches can push in-flight past max.num.cluster.movements."""
        with self._lock:
            return max(0, self._caps.cluster_inter_broker
                       - self._cluster_inter_in_flight)

    def leadership_cap(self) -> int:
        return self._caps.leadership_cluster

    def leadership_per_broker_cap(self) -> int:
        return self._caps.leadership_per_broker

    def intra_broker_per_broker_cap(self) -> int:
        return self._caps.intra_broker_per_broker

    def cluster_intra_broker_headroom(self, in_flight: int) -> int:
        """Cluster-wide intra-broker batch bound: the reference caps total
        in-flight movements by max.num.cluster.movements across phases
        (Executor.java:1672 batch sizing); we reuse the cluster cap."""
        return max(0, self._caps.cluster_inter_broker - in_flight)

    # ---- in-flight accounting --------------------------------------------
    def acquire_inter_broker(self, brokers: tuple[int, ...]) -> None:
        with self._lock:
            for b in brokers:
                self._inter_in_flight[b] = self._inter_in_flight.get(b, 0) + 1
            self._cluster_inter_in_flight += 1

    def release_inter_broker(self, brokers: tuple[int, ...]) -> None:
        with self._lock:
            for b in brokers:
                self._inter_in_flight[b] = max(0, self._inter_in_flight.get(b, 0) - 1)
            self._cluster_inter_in_flight = max(0, self._cluster_inter_in_flight - 1)

    # ---- adaptive adjustment (ConcurrencyAdjuster) ------------------------
    def adjust(self, cluster_healthy: bool, has_under_min_isr: bool,
               frozen: frozenset[str] = frozenset()) -> None:
        """One adjuster tick: halve inter-broker concurrency under min-ISR
        pressure, step up toward 2× base when healthy
        (Executor.java:465-683). ``frozen`` names ConcurrencyCaps fields
        carrying a per-execution OPERATOR override — those dimensions are
        left alone (the reference skips user-requested dimensions); all
        others keep adjusting, including the min-ISR safety step-down."""
        with self._lock:
            if not self._min_isr_based_adjustment:
                # ADMIN min_isr_based_concurrency_adjustment=false: the
                # adjuster ignores (At/Under)MinISR pressure entirely
                # (Executor.java min.isr-based adjustment toggle).
                has_under_min_isr = False
            if not self._adjuster_enabled["INTER_BROKER_REPLICA"]:
                frozen = frozen | {"inter_broker_per_broker"}
            if not self._adjuster_enabled["LEADERSHIP"]:
                frozen = frozen | {"leadership_cluster"}
            if "inter_broker_per_broker" not in frozen:
                cap = self._caps.inter_broker_per_broker
                if has_under_min_isr:
                    cap = max(self.MIN_INTER_BROKER, cap // 2)
                elif cluster_healthy:
                    cap = min(self._base.inter_broker_per_broker
                              * self.MAX_INTER_BROKER_MULTIPLIER, cap + 1)
                # Unhealthy WITHOUT min-ISR pressure (e.g. offline replicas
                # mid-drain — the very workload self-healing is executing)
                # HOLDS the cap: decrementing here would decay recovery
                # throughput to the minimum for the whole execution, since
                # health only returns once recovery finishes.
                self._caps.inter_broker_per_broker = cap

            if "leadership_cluster" not in frozen:
                lcap = self._caps.leadership_cluster
                if has_under_min_isr:
                    lcap = max(self.MIN_LEADERSHIP, lcap // 2)
                elif cluster_healthy:
                    lcap = min(self._base.leadership_cluster, lcap + 100)
                self._caps.leadership_cluster = lcap

    def set_adjuster_enabled(self, concurrency_type: str,
                             enabled: bool) -> bool:
        """Toggle the adaptive adjuster for one ConcurrencyType (the ADMIN
        endpoint's (en|dis)able_concurrency_adjuster_for). Returns the
        previous setting; unknown types raise (a typo must not no-op)."""
        key = concurrency_type.upper()
        if key not in self._adjuster_enabled:
            raise ValueError(
                f"unknown concurrency type {concurrency_type!r}; expected "
                f"one of {', '.join(self.ADJUSTER_TYPES)}")
        with self._lock:
            old = self._adjuster_enabled[key]
            self._adjuster_enabled[key] = enabled
            return old

    def set_min_isr_based_adjustment(self, enabled: bool) -> bool:
        with self._lock:
            old = self._min_isr_based_adjustment
            self._min_isr_based_adjustment = enabled
            return old

    def snapshot(self) -> ConcurrencyCaps:
        with self._lock:
            return dataclasses.replace(self._caps)

    def restore(self, caps: ConcurrencyCaps) -> None:
        """Undo per-execution overrides (the reference resets requested
        concurrency when the execution finishes)."""
        with self._lock:
            for f in dataclasses.fields(ConcurrencyCaps):
                setattr(self._caps, f.name, getattr(caps, f.name))

    def state(self) -> dict:
        with self._lock:
            return {
                "interBrokerPerBroker": self._caps.inter_broker_per_broker,
                "clusterInterBroker": self._caps.cluster_inter_broker,
                "leadershipCluster": self._caps.leadership_cluster,
                "interBrokerInFlight": self._cluster_inter_in_flight,
                "adjusterEnabled": dict(self._adjuster_enabled),
                "minIsrBasedAdjustment": self._min_isr_based_adjustment,
            }
