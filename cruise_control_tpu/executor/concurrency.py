"""Execution concurrency control.

Reference parity: executor/concurrency/ExecutionConcurrencyManager.java (355;
per-broker and cluster-wide caps for inter-broker, intra-broker and
leadership actions) and the ConcurrencyAdjuster inside Executor.java:465-683
(periodically raises/lowers caps from broker health: under-min-ISR state
halves throughput, healthy metrics step it up).
"""

from __future__ import annotations

import dataclasses
import threading


@dataclasses.dataclass
class ConcurrencyCaps:
    """Defaults follow config/cruisecontrol.properties
    (num.concurrent.partition.movements.per.broker=10,
    max.num.cluster.partition.movements=1250,
    num.concurrent.intra.broker.partition.movements=2,
    num.concurrent.leader.movements=1000)."""

    inter_broker_per_broker: int = 10
    cluster_inter_broker: int = 1250
    intra_broker_per_broker: int = 2
    leadership_cluster: int = 1000
    leadership_per_broker: int = 250


@dataclasses.dataclass(frozen=True)
class ConcurrencyAdjusterConfig:
    """The adjuster's tuning surface (ExecutorConfig.java:340-583 —
    AIMD per concurrency type: additive increase while healthy,
    multiplicative decrease under (At/Under)MinISR pressure or broker
    metric-limit violations, clamped to [min, max])."""

    additive_increase_inter_broker: int = 1
    additive_increase_leadership: int = 100
    additive_increase_leadership_per_broker: int = 25
    multiplicative_decrease_inter_broker: float = 2.0
    multiplicative_decrease_leadership: float = 2.0
    multiplicative_decrease_leadership_per_broker: float = 2.0
    min_partition_movements_per_broker: int = 1
    max_partition_movements_per_broker: int = 12
    min_leadership_movements: int = 100
    max_leadership_movements: int = 1100
    min_leadership_movements_per_broker: int = 25
    max_leadership_movements_per_broker: int = 500
    leadership_per_broker_enabled: bool = False
    limit_log_flush_time_ms: float = 2000.0
    limit_follower_fetch_local_time_ms: float = 500.0
    limit_produce_local_time_ms: float = 1000.0
    limit_consumer_fetch_local_time_ms: float = 500.0
    limit_request_queue_size: float = 1000.0
    min_brokers_violate_metric_limit: int = 2
    num_min_isr_check: int = 5
    # Per-type enablement seeds (Executor.java:230-237): operators set the
    # concurrency.adjuster.*.enabled keys; the ADMIN endpoint can still
    # flip them at runtime. INTRA_BROKER_REPLICA is hard-disabled in the
    # reference (pending linkedin/cruise-control#1299) — kept OFF here for
    # the same semantics.
    inter_broker_enabled: bool = True
    leadership_enabled: bool = True
    min_isr_check_enabled: bool = False

    # metric-name → limit-field mapping (KafkaMetricDef BrokerMetric names;
    # ConcurrencyAdjuster's CONCURRENCY_ADJUSTER_METRICS).
    LIMIT_METRICS = (
        ("BROKER_LOG_FLUSH_TIME_MS_999TH", "limit_log_flush_time_ms"),
        ("BROKER_FOLLOWER_FETCH_LOCAL_TIME_MS_999TH",
         "limit_follower_fetch_local_time_ms"),
        ("BROKER_PRODUCE_LOCAL_TIME_MS_999TH", "limit_produce_local_time_ms"),
        ("BROKER_CONSUMER_FETCH_LOCAL_TIME_MS_999TH",
         "limit_consumer_fetch_local_time_ms"),
        ("BROKER_REQUEST_QUEUE_SIZE", "limit_request_queue_size"),
    )

    @classmethod
    def from_config(cls, cfg) -> "ConcurrencyAdjusterConfig":
        g = cfg.get_int
        return cls(
            additive_increase_inter_broker=g(
                "concurrency.adjuster.additive.increase.inter.broker.replica"),
            additive_increase_leadership=g(
                "concurrency.adjuster.additive.increase.leadership"),
            additive_increase_leadership_per_broker=g(
                "concurrency.adjuster.additive.increase.leadership.per.broker"),
            multiplicative_decrease_inter_broker=cfg.get_double(
                "concurrency.adjuster.multiplicative.decrease.inter.broker.replica"),
            multiplicative_decrease_leadership=cfg.get_double(
                "concurrency.adjuster.multiplicative.decrease.leadership"),
            multiplicative_decrease_leadership_per_broker=cfg.get_double(
                "concurrency.adjuster.multiplicative.decrease.leadership.per.broker"),
            min_partition_movements_per_broker=g(
                "concurrency.adjuster.min.partition.movements.per.broker"),
            max_partition_movements_per_broker=g(
                "concurrency.adjuster.max.partition.movements.per.broker"),
            min_leadership_movements=g(
                "concurrency.adjuster.min.leadership.movements"),
            max_leadership_movements=g(
                "concurrency.adjuster.max.leadership.movements"),
            min_leadership_movements_per_broker=g(
                "concurrency.adjuster.min.leadership.movements.per.broker"),
            max_leadership_movements_per_broker=g(
                "concurrency.adjuster.max.leadership.movements.per.broker"),
            leadership_per_broker_enabled=cfg.get_boolean(
                "concurrency.adjuster.leadership.per.broker.enabled"),
            limit_log_flush_time_ms=cfg.get_double(
                "concurrency.adjuster.limit.log.flush.time.ms"),
            limit_follower_fetch_local_time_ms=cfg.get_double(
                "concurrency.adjuster.limit.follower.fetch.local.time.ms"),
            limit_produce_local_time_ms=cfg.get_double(
                "concurrency.adjuster.limit.produce.local.time.ms"),
            limit_consumer_fetch_local_time_ms=cfg.get_double(
                "concurrency.adjuster.limit.consumer.fetch.local.time.ms"),
            limit_request_queue_size=cfg.get_double(
                "concurrency.adjuster.limit.request.queue.size"),
            min_brokers_violate_metric_limit=g(
                "min.num.brokers.violate.metric.limit.to.decrease.cluster.concurrency"),
            num_min_isr_check=g("concurrency.adjuster.num.min.isr.check"),
            inter_broker_enabled=cfg.get_boolean(
                "concurrency.adjuster.inter.broker.replica.enabled"),
            leadership_enabled=cfg.get_boolean(
                "concurrency.adjuster.leadership.enabled"),
            min_isr_check_enabled=cfg.get_boolean(
                "concurrency.adjuster.min.isr.check.enabled"),
        )

    def brokers_violating_limits(self, broker_metrics) -> int:
        """#brokers whose latest metrics exceed ANY adjuster limit
        (withinConcurrencyAdjusterLimit, Executor.java:465-683).
        ``broker_metrics``: {broker_id: {metric_name: value}}."""
        n = 0
        for metrics in (broker_metrics or {}).values():
            for name, field in self.LIMIT_METRICS:
                v = metrics.get(name)
                if v is not None and v > getattr(self, field):
                    n += 1
                    break
        return n


class ExecutionConcurrencyManager:
    """Tracks caps + in-flight counts; thread-safe
    (ExecutionConcurrencyManager.java)."""

    # ConcurrencyType names accepted by the ADMIN endpoint's
    # (en|dis)able_concurrency_adjuster_for toggles (ConcurrencyType.java).
    ADJUSTER_TYPES = ("INTER_BROKER_REPLICA", "INTRA_BROKER_REPLICA",
                      "LEADERSHIP")

    def __init__(self, caps: ConcurrencyCaps | None = None,
                 adjuster: ConcurrencyAdjusterConfig | None = None):
        self._caps = caps or ConcurrencyCaps()
        self._base = dataclasses.replace(self._caps)
        self._adj = adjuster or ConcurrencyAdjusterConfig()
        self._lock = threading.Lock()
        self._inter_in_flight: dict[int, int] = {}   # broker -> count
        self._cluster_inter_in_flight = 0
        # Seeded from config (Executor.java:230-237); INTRA_BROKER_REPLICA
        # stays disabled (reference hard-disables it pending
        # linkedin/cruise-control#1299). The ADMIN endpoint toggles these
        # at runtime.
        self._adjuster_enabled = {
            "INTER_BROKER_REPLICA": self._adj.inter_broker_enabled,
            "INTRA_BROKER_REPLICA": False,
            "LEADERSHIP": self._adj.leadership_enabled,
        }
        self._min_isr_based_adjustment = self._adj.min_isr_check_enabled

    @property
    def adjuster_config(self) -> ConcurrencyAdjusterConfig:
        return self._adj

    # ---- capacity queries -------------------------------------------------
    def inter_broker_headroom(self, broker: int) -> int:
        with self._lock:
            per = self._caps.inter_broker_per_broker - self._inter_in_flight.get(broker, 0)
            cluster = self._caps.cluster_inter_broker - self._cluster_inter_in_flight
            return max(0, min(per, cluster))

    def cluster_inter_broker_headroom(self) -> int:
        """Remaining cluster-wide inter-broker movement capacity; batch
        sizes must be bounded by this, not the raw cap, or concurrent
        batches can push in-flight past max.num.cluster.movements."""
        with self._lock:
            return max(0, self._caps.cluster_inter_broker
                       - self._cluster_inter_in_flight)

    def leadership_cap(self) -> int:
        return self._caps.leadership_cluster

    def leadership_per_broker_cap(self) -> int:
        return self._caps.leadership_per_broker

    def intra_broker_per_broker_cap(self) -> int:
        return self._caps.intra_broker_per_broker

    def cluster_intra_broker_headroom(self, in_flight: int) -> int:
        """Cluster-wide intra-broker batch bound: the reference caps total
        in-flight movements by max.num.cluster.movements across phases
        (Executor.java:1672 batch sizing); we reuse the cluster cap."""
        return max(0, self._caps.cluster_inter_broker - in_flight)

    # ---- in-flight accounting --------------------------------------------
    def acquire_inter_broker(self, brokers: tuple[int, ...]) -> None:
        with self._lock:
            for b in brokers:
                self._inter_in_flight[b] = self._inter_in_flight.get(b, 0) + 1
            self._cluster_inter_in_flight += 1

    def release_inter_broker(self, brokers: tuple[int, ...]) -> None:
        with self._lock:
            for b in brokers:
                self._inter_in_flight[b] = max(0, self._inter_in_flight.get(b, 0) - 1)
            self._cluster_inter_in_flight = max(0, self._cluster_inter_in_flight - 1)

    # ---- adaptive adjustment (ConcurrencyAdjuster) ------------------------
    def adjust(self, cluster_healthy: bool, has_under_min_isr: bool,
               frozen: frozenset[str] = frozenset(),
               brokers_violating_metric_limits: int = 0) -> None:
        """One AIMD adjuster tick (Executor.java:465-683): multiplicative
        decrease under (At/Under)MinISR pressure OR when at least
        ``min.num.brokers.violate.metric.limit...`` brokers exceed a broker
        metric limit; additive increase toward the max cap while healthy.
        ``frozen`` names ConcurrencyCaps fields carrying a per-execution
        OPERATOR override — those dimensions are left alone (the reference
        skips user-requested dimensions); all others keep adjusting,
        including the safety step-down."""
        adj = self._adj
        with self._lock:
            if not self._min_isr_based_adjustment:
                # ADMIN min_isr_based_concurrency_adjustment=false: the
                # adjuster ignores (At/Under)MinISR pressure entirely
                # (Executor.java min.isr-based adjustment toggle).
                has_under_min_isr = False
            decrease = has_under_min_isr or (
                brokers_violating_metric_limits
                >= adj.min_brokers_violate_metric_limit)
            if not self._adjuster_enabled["INTER_BROKER_REPLICA"]:
                frozen = frozen | {"inter_broker_per_broker"}
            if not self._adjuster_enabled["LEADERSHIP"]:
                frozen = frozen | {"leadership_cluster",
                                   "leadership_per_broker"}
            if not adj.leadership_per_broker_enabled:
                frozen = frozen | {"leadership_per_broker"}

            def aimd(cap, dec, add, lo, hi):
                if decrease:
                    return max(lo, int(cap / dec))
                if cluster_healthy:
                    return min(hi, cap + add)
                # Unhealthy WITHOUT decrease pressure (e.g. offline
                # replicas mid-drain — the very workload self-healing is
                # executing) HOLDS the cap: decrementing here would decay
                # recovery throughput to the minimum for the whole
                # execution, since health only returns once recovery
                # finishes.
                return cap

            if "inter_broker_per_broker" not in frozen:
                self._caps.inter_broker_per_broker = aimd(
                    self._caps.inter_broker_per_broker,
                    adj.multiplicative_decrease_inter_broker,
                    adj.additive_increase_inter_broker,
                    adj.min_partition_movements_per_broker,
                    adj.max_partition_movements_per_broker)
            if "leadership_cluster" not in frozen:
                self._caps.leadership_cluster = aimd(
                    self._caps.leadership_cluster,
                    adj.multiplicative_decrease_leadership,
                    adj.additive_increase_leadership,
                    adj.min_leadership_movements,
                    adj.max_leadership_movements)
            if "leadership_per_broker" not in frozen:
                self._caps.leadership_per_broker = aimd(
                    self._caps.leadership_per_broker,
                    adj.multiplicative_decrease_leadership_per_broker,
                    adj.additive_increase_leadership_per_broker,
                    adj.min_leadership_movements_per_broker,
                    adj.max_leadership_movements_per_broker)

    def set_adjuster_enabled(self, concurrency_type: str,
                             enabled: bool) -> bool:
        """Toggle the adaptive adjuster for one ConcurrencyType (the ADMIN
        endpoint's (en|dis)able_concurrency_adjuster_for). Returns the
        previous setting; unknown types raise (a typo must not no-op)."""
        key = concurrency_type.upper()
        if key not in self._adjuster_enabled:
            raise ValueError(
                f"unknown concurrency type {concurrency_type!r}; expected "
                f"one of {', '.join(self.ADJUSTER_TYPES)}")
        with self._lock:
            old = self._adjuster_enabled[key]
            self._adjuster_enabled[key] = enabled
            return old

    def set_min_isr_based_adjustment(self, enabled: bool) -> bool:
        with self._lock:
            old = self._min_isr_based_adjustment
            self._min_isr_based_adjustment = enabled
            return old

    def snapshot(self) -> ConcurrencyCaps:
        with self._lock:
            return dataclasses.replace(self._caps)

    def restore(self, caps: ConcurrencyCaps) -> None:
        """Undo per-execution overrides (the reference resets requested
        concurrency when the execution finishes)."""
        with self._lock:
            for f in dataclasses.fields(ConcurrencyCaps):
                setattr(self._caps, f.name, getattr(caps, f.name))

    def state(self) -> dict:
        with self._lock:
            return {
                "interBrokerPerBroker": self._caps.inter_broker_per_broker,
                "clusterInterBroker": self._caps.cluster_inter_broker,
                "leadershipCluster": self._caps.leadership_cluster,
                "interBrokerInFlight": self._cluster_inter_in_flight,
                "adjusterEnabled": dict(self._adjuster_enabled),
                "minIsrBasedAdjustment": self._min_isr_based_adjustment,
            }
