"""Topic min.insync.replicas cache + under-min-ISR evaluation.

Reference parity: common/TopicMinIsrCache.java — the ConcurrencyAdjuster
(Executor.java:465-683) consults cached topic ``min.insync.replicas``
values against live ISR sizes to decide whether to throttle execution.
Config describes are rate-limited by a TTL so the poll loop does not spam
describeTopicConfigs.
"""

from __future__ import annotations

import time
from typing import Iterable, Mapping

from .admin import PartitionState

DEFAULT_MIN_ISR = 1


class TopicMinIsrCache:
    def __init__(self, ttl_s: float = 30.0):
        self._ttl_s = ttl_s
        self._cache: dict[str, tuple[float, int]] = {}

    def min_isr_by_topic(self, admin, topics: Iterable[str]) -> dict[str, int]:
        now = time.time()
        missing = [t for t in topics
                   if t not in self._cache
                   or now - self._cache[t][0] > self._ttl_s]
        if missing:
            try:
                configs = admin.describe_topic_configs(missing)
            except Exception:  # noqa: BLE001 — degrade to defaults
                configs = {}
            for t in missing:
                # ccsa: ok[CCSA005] KAFKA topic-config key space
                raw = (configs.get(t) or {}).get("min.insync.replicas")
                try:
                    value = int(raw) if raw is not None else DEFAULT_MIN_ISR
                except (TypeError, ValueError):
                    value = DEFAULT_MIN_ISR
                self._cache[t] = (now, value)
        return {t: self._cache[t][1] for t in topics if t in self._cache}


def cluster_isr_state(parts: Mapping[tuple[str, int], PartitionState],
                      alive: set[int],
                      min_isr: Mapping[str, int]) -> tuple[bool, bool]:
    """(cluster_healthy, has_under_min_isr) from a metadata snapshot:
    healthy = every replica sits on an alive broker (no offline replicas);
    under-min-ISR = some partition's live ISR is below its topic's
    min.insync.replicas (ExecutionUtils.isClusterConcurrencyDecreaseNeeded)."""
    healthy = True
    under = False
    for p in parts.values():
        if any(b not in alive for b in p.replicas):
            healthy = False
        live_isr = sum(1 for b in p.isr if b in alive)
        if live_isr < min_isr.get(p.topic, DEFAULT_MIN_ISR):
            under = True
            healthy = False
    return healthy, under
