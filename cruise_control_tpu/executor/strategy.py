"""Replica-movement ordering strategies.

Reference parity: executor/strategy/ (539 LoC): ReplicaMovementStrategy SPI
with chain()-able comparators — BaseReplicaMovementStrategy,
PrioritizeSmallReplicaMovementStrategy, PrioritizeLargeReplicaMovementStrategy,
PostponeUrpReplicaMovementStrategy, PrioritizeMinIsrWithOfflineReplicasStrategy.
A strategy sorts the pending inter-broker tasks; chained strategies break
ties left to right, with BaseReplicaMovementStrategy (execution id order)
always the final tiebreak.
"""

from __future__ import annotations

from typing import Callable, Iterable, Protocol

from .task import ExecutionTask


class ClusterInfo(Protocol):
    """Minimal cluster facts the strategies consult (the reference passes a
    Kafka ``Cluster`` + min-ISR cache; here a narrow protocol the admin
    backend implements)."""

    def partition_size(self, topic: str, partition: int) -> float: ...
    def is_under_replicated(self, topic: str, partition: int) -> bool: ...
    def is_under_min_isr_with_offline(self, topic: str, partition: int) -> bool: ...


class ReplicaMovementStrategy:
    """SPI: returns a sort key for one task; lower sorts earlier
    (ReplicaMovementStrategy.java)."""

    name = "AbstractReplicaMovementStrategy"

    def key(self, task: ExecutionTask, cluster: ClusterInfo):
        return 0

    def chain(self, nxt: "ReplicaMovementStrategy") -> "ReplicaMovementStrategy":
        return _Chained(self, nxt)

    def sort(self, tasks: Iterable[ExecutionTask],
             cluster: ClusterInfo) -> list[ExecutionTask]:
        final = self.chain(BaseReplicaMovementStrategy())
        return sorted(tasks, key=lambda t: final.key(t, cluster))


class _Chained(ReplicaMovementStrategy):
    def __init__(self, first: ReplicaMovementStrategy, second: ReplicaMovementStrategy):
        self._first, self._second = first, second
        self.name = f"{first.name}->{second.name}"

    def key(self, task, cluster):
        fk = self._first.key(task, cluster)
        sk = self._second.key(task, cluster)
        fk = fk if isinstance(fk, tuple) else (fk,)
        sk = sk if isinstance(sk, tuple) else (sk,)
        return fk + sk


class BaseReplicaMovementStrategy(ReplicaMovementStrategy):
    """Execution-id order (BaseReplicaMovementStrategy.java)."""

    name = "BaseReplicaMovementStrategy"

    def key(self, task, cluster):
        return task.execution_id


class PrioritizeSmallReplicaMovementStrategy(ReplicaMovementStrategy):
    name = "PrioritizeSmallReplicaMovementStrategy"

    def key(self, task, cluster):
        return cluster.partition_size(*task.topic_partition)


class PrioritizeLargeReplicaMovementStrategy(ReplicaMovementStrategy):
    name = "PrioritizeLargeReplicaMovementStrategy"

    def key(self, task, cluster):
        return -cluster.partition_size(*task.topic_partition)


class PostponeUrpReplicaMovementStrategy(ReplicaMovementStrategy):
    """Move healthy (non-under-replicated) partitions first
    (PostponeUrpReplicaMovementStrategy.java)."""

    name = "PostponeUrpReplicaMovementStrategy"

    def key(self, task, cluster):
        return 1 if cluster.is_under_replicated(*task.topic_partition) else 0


class PrioritizeMinIsrWithOfflineReplicasStrategy(ReplicaMovementStrategy):
    """(At/Under)MinISR partitions with offline replicas first
    (PrioritizeMinIsrWithOfflineReplicasStrategy.java)."""

    name = "PrioritizeMinIsrWithOfflineReplicasStrategy"

    def key(self, task, cluster):
        return 0 if cluster.is_under_min_isr_with_offline(*task.topic_partition) else 1


STRATEGIES: dict[str, Callable[[], ReplicaMovementStrategy]] = {
    cls.name: cls for cls in (
        BaseReplicaMovementStrategy,
        PrioritizeSmallReplicaMovementStrategy,
        PrioritizeLargeReplicaMovementStrategy,
        PostponeUrpReplicaMovementStrategy,
        PrioritizeMinIsrWithOfflineReplicasStrategy,
    )
}


def strategy_chain(names: Iterable[str]) -> ReplicaMovementStrategy:
    """Build a chained strategy from config names
    (default.replica.movement.strategies semantics)."""
    chain: ReplicaMovementStrategy | None = None
    for n in names:
        if n not in STRATEGIES:
            raise ValueError(f"unknown replica movement strategy {n!r}")
        s = STRATEGIES[n]()
        chain = s if chain is None else chain.chain(s)
    return chain or BaseReplicaMovementStrategy()
