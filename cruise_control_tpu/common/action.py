"""Balancing action taxonomy.

Reference parity: analyzer/common/ActionType.java (INTER_BROKER_REPLICA_MOVEMENT,
LEADERSHIP_MOVEMENT, INTER_BROKER_REPLICA_SWAP, INTRA_BROKER_REPLICA_MOVEMENT,
INTRA_BROKER_REPLICA_SWAP) and ActionAcceptance.java (ACCEPT, REPLICA_REJECT,
BROKER_REJECT).

In the tensor solver a candidate action is a row of integers
``(action_type, partition, src_slot, dst_broker, dst_slot_partition)`` and
acceptance is a vectorized tri-state int8 array over candidates.
"""

from __future__ import annotations

import enum


class ActionType(enum.IntEnum):
    INTER_BROKER_REPLICA_MOVEMENT = 0
    LEADERSHIP_MOVEMENT = 1
    INTER_BROKER_REPLICA_SWAP = 2
    INTRA_BROKER_REPLICA_MOVEMENT = 3
    INTRA_BROKER_REPLICA_SWAP = 4


class ActionAcceptance(enum.IntEnum):
    """Tri-state acceptance; BROKER_REJECT prunes the destination broker for
    the remainder of a swap search (AbstractGoal.java:332-335)."""

    ACCEPT = 0
    REPLICA_REJECT = 1
    BROKER_REJECT = 2
