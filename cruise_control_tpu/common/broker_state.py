"""Broker / disk liveness states.

Reference parity: model/Broker.java:37 ``State {ALIVE, DEAD, NEW, DEMOTED,
BAD_DISKS}`` and model/Disk.java:32 ``State {ALIVE, DEAD}``.

Encoded as small ints so the tensor model can carry a ``broker_state[B]``
int8 array and goal kernels can build masks with simple comparisons.
"""

from __future__ import annotations

import enum


class BrokerState(enum.IntEnum):
    ALIVE = 0
    DEAD = 1
    NEW = 2
    DEMOTED = 3
    BAD_DISKS = 4


class DiskState(enum.IntEnum):
    ALIVE = 0
    DEAD = 1
