from .resources import Resource, NUM_RESOURCES, EPSILON_PERCENT
from .broker_state import BrokerState, DiskState
from .action import ActionType, ActionAcceptance
