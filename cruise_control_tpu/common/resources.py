"""Resource taxonomy for broker/partition load accounting.

Reference parity: cruise-control/src/main/java/com/linkedin/kafka/
cruisecontrol/common/Resource.java (CPU, NW_IN, NW_OUT, DISK with
per-resource epsilon and balancing eligibility).

In the tensor model a resource is an integer axis index into the trailing
``R`` dimension of load/capacity arrays, so goal kernels can be written once
and specialised per resource by indexing.
"""

from __future__ import annotations

import enum


class Resource(enum.IntEnum):
    """Axis indices of the resource dimension in load/capacity tensors."""

    CPU = 0
    NW_IN = 1
    NW_OUT = 2
    DISK = 3

    @property
    def is_host_resource(self) -> bool:
        # Reference: Resource.java — CPU, NW_IN, NW_OUT are host resources.
        return self in (Resource.CPU, Resource.NW_IN, Resource.NW_OUT)

    @property
    def is_broker_resource(self) -> bool:
        return True


NUM_RESOURCES = len(Resource)

# Reference: Resource.java:28-31 — epsilon chosen so that summing ~800k
# replica float loads stays within precision; we use float32 on device and
# the same relative epsilon for comparisons.
EPSILON_PERCENT = 0.0008

# Per-resource epsilon scale (mirrors Resource.java per-resource epsilon()).
RESOURCE_EPSILON = {
    Resource.CPU: 1e-4,
    Resource.NW_IN: 1e-2,
    Resource.NW_OUT: 1e-2,
    Resource.DISK: 1e-2,
}
