"""Serving front door (round 20).

The api layer's user-facing machinery, factored into one subsystem:

- ``tasks``: the unified async task engine — bounded per-class queues
  (VIEWER-cheap vs SOLVER-heavy), task lifecycle
  (queued → running → done/failed/evicted), per-class worker pools whose
  solver threads only WAIT on FleetScheduler futures (device work always
  runs under the scheduler's fairness, never on a handler thread).
- ``cache``: the model-generation-keyed response cache — a response's
  identity is (cluster, endpoint, canonical params, load-model
  generation, goal-chain fingerprint); byte-identical replays until the
  generation or the configured chain moves.
- ``admission``: queue-depth-aware shedding layered above the
  per-cluster breaker — 429 + Retry-After derived from observed
  per-class service rates.
- ``loadgen``: the deterministic load harness — a seeded, wall-clock-free
  open-loop arrival schedule over a mixed request-class profile, driving
  the REAL transport-independent api (`bench.py --serving`).
"""

from .admission import AdmissionController, AdmissionShedError
from .cache import (
    CACHEABLE_ENDPOINTS, COALESCIBLE_ENDPOINTS, ResponseCache,
    canonical_params,
)
from .tasks import (
    AsyncTaskEngine, TaskClass, TaskQueueFullError, task_class_of,
)

__all__ = [
    "AdmissionController", "AdmissionShedError", "AsyncTaskEngine",
    "CACHEABLE_ENDPOINTS", "COALESCIBLE_ENDPOINTS", "ResponseCache",
    "TaskClass", "TaskQueueFullError", "canonical_params",
    "task_class_of",
]
