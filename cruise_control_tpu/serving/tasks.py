"""Unified async task engine (round 20 serving front door).

Reference parity: servlet/UserTaskManager.java runs every async endpoint
on one undifferentiated thread pool. At fleet scale that conflates two
very different request classes: VIEWER reads (load, partition_load — a
model build at most) and SOLVER requests (proposals, rebalance, futures —
real device time). The engine gives each class its OWN bounded queue and
worker pool with an explicit task lifecycle
(queued → running → done/failed → evicted), so

- queue depth per class is an observable admission signal
  (serving.admission), not an opaque pool backlog;
- a flood of solver requests can never exhaust the threads a dashboard's
  state polls ride on;
- SOLVER workers only ever WAIT on FleetScheduler futures — the api layer
  wraps solver work as ON_DEMAND scheduler jobs, so the engine bounds
  concurrent *waiters* while the device itself stays under the
  scheduler's fairness and starvation bound.

The engine is deterministic machinery (CCSA004): all timestamps ride the
injected ``monotonic`` seam, service-rate EWMAs are pure functions of
observed durations.
"""

from __future__ import annotations

import collections
import dataclasses
import enum
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable

from ..utils.sensors import SENSORS


class TaskClass(enum.Enum):
    VIEWER = "VIEWER"
    SOLVER = "SOLVER"


# Device-heavy endpoints by NAME (the api layer's _SOLVER_ENDPOINTS,
# mirrored as strings so the engine has no import edge back into api/).
SOLVER_CLASS_ENDPOINTS = frozenset({
    "PROPOSALS", "REBALANCE", "ADD_BROKER", "REMOVE_BROKER",
    "DEMOTE_BROKER", "FIX_OFFLINE_REPLICAS", "TOPIC_CONFIGURATION",
    "REMOVE_DISKS", "COMPARE_FUTURES",
})

# Seed service-time estimates until the EWMA has real observations: a
# viewer read is a model build at most, a solver request is device time.
_DEFAULT_SERVICE_S = {TaskClass.VIEWER: 0.05, TaskClass.SOLVER: 2.0}
_EWMA_ALPHA = 0.2

# Finished task records kept for lifecycle queries (GET /user_tasks);
# oldest evicted past this bound. The RESULTS live in the
# UserTaskManager's per-class retention caches, not here.
_MAX_RECORDS = 1024


def task_class_of(endpoint: str) -> TaskClass:
    return TaskClass.SOLVER if endpoint in SOLVER_CLASS_ENDPOINTS \
        else TaskClass.VIEWER


class TaskQueueFullError(RuntimeError):
    """A class queue at hard capacity — the backstop bound above the
    admission layer's (softer) depth threshold. Maps to HTTP 429 +
    Retry-After."""

    def __init__(self, klass: TaskClass, capacity: int,
                 retry_after_s: float):
        super().__init__(
            f"{klass.value} task queue at capacity ({capacity}); "
            "retry later")
        self.klass = klass
        self.capacity = capacity
        self.retry_after_s = retry_after_s


@dataclasses.dataclass
class EngineTask:
    """Lifecycle record of one engine submission. ``evicted`` means the
    UserTaskManager's retention dropped the stored result — the record
    outlives the result so a late poll sees WHY the id is gone."""

    task_id: str
    endpoint: str
    klass: TaskClass
    lifecycle: str = "queued"  # queued|running|done|failed|evicted
    enqueued_s: float = 0.0
    started_s: float = 0.0
    finished_s: float = 0.0


class AsyncTaskEngine:
    def __init__(self, viewer_capacity: int = 64,
                 solver_capacity: int = 32,
                 viewer_threads: int = 4, solver_threads: int = 2,
                 monotonic: Callable[[], float] = time.monotonic):
        self._monotonic = monotonic
        self._cv = threading.Condition()
        self._shutdown = False
        self._capacity = {TaskClass.VIEWER: int(viewer_capacity),
                          TaskClass.SOLVER: int(solver_capacity)}
        self._queues: dict[TaskClass, collections.deque] = {
            k: collections.deque() for k in TaskClass}
        self._records: collections.OrderedDict[str, EngineTask] = \
            collections.OrderedDict()
        self._ewma_s: dict[TaskClass, float | None] = {
            k: None for k in TaskClass}
        self.completed = {k: 0 for k in TaskClass}
        self.evicted = 0
        self._threads: list[threading.Thread] = []
        counts = {TaskClass.VIEWER: int(viewer_threads),
                  TaskClass.SOLVER: int(solver_threads)}
        for klass, n in counts.items():
            for i in range(n):
                t = threading.Thread(
                    target=self._worker, args=(klass,),
                    name=f"serving-{klass.value.lower()}-{i}", daemon=True)
                t.start()
                self._threads.append(t)

    # -- submission --------------------------------------------------------
    def submit(self, endpoint: str, fn: Callable[[], Any],
               task_id: str) -> tuple[Future, EngineTask]:
        """Enqueue ``fn`` on the endpoint's class queue. Raises
        TaskQueueFullError at capacity. After shutdown the call runs
        INLINE (the FleetScheduler's submit-after-shutdown discipline:
        teardown races resolve to synchronous execution, never a hang)."""
        klass = task_class_of(endpoint)
        rec = EngineTask(task_id=task_id, endpoint=endpoint, klass=klass,
                         enqueued_s=self._monotonic())
        fut: Future = Future()
        with self._cv:
            if self._shutdown:
                self._record_locked(rec)
                self._run(rec, fn, fut, inline=True)
                return fut, rec
            depth = len(self._queues[klass])
            if depth >= self._capacity[klass]:
                retry = self._retry_after_locked(klass, depth + 1)
                raise TaskQueueFullError(klass, self._capacity[klass],
                                         retry)
            self._record_locked(rec)
            self._queues[klass].append((rec, fn, fut))
            depth += 1
            # One condition serves BOTH class queues: notify_all, because
            # a single notify may wake only a worker of the OTHER class,
            # which re-waits and swallows the wakeup — the queued task
            # would sit until the next submission.
            self._cv.notify_all()
        SENSORS.count("serving_tasks_submitted",
                      labels={"class": klass.value})
        SENSORS.gauge("serving_queue_depth", float(depth),
                      labels={"class": klass.value})
        return fut, rec

    def _record_locked(self, rec: EngineTask) -> None:
        self._records[rec.task_id] = rec
        while len(self._records) > _MAX_RECORDS:
            self._records.popitem(last=False)

    # -- workers -----------------------------------------------------------
    def _worker(self, klass: TaskClass) -> None:
        q = self._queues[klass]
        while True:
            with self._cv:
                while not q and not self._shutdown:
                    self._cv.wait()
                if not q:
                    return  # shutdown with the queue drained
                rec, fn, fut = q.popleft()
            self._run(rec, fn, fut)

    def _run(self, rec: EngineTask, fn, fut: Future,
             inline: bool = False) -> None:
        if not inline and not fut.set_running_or_notify_cancel():
            rec.lifecycle = "evicted"
            return
        rec.lifecycle = "running"
        rec.started_s = self._monotonic()
        # The REAL wait distribution behind the Retry-After EWMA: queue
        # time per class, observable instead of EWMA-internal.
        SENSORS.observe("serving_queue_wait_seconds",
                        max(0.0, rec.started_s - rec.enqueued_s),
                        labels={"class": rec.klass.value})
        try:
            result = fn()
        except BaseException as e:  # noqa: BLE001 — future carries it
            rec.lifecycle = "failed"
            self._finish(rec)
            fut.set_exception(e)
        else:
            rec.lifecycle = "done"
            self._finish(rec)
            fut.set_result(result)

    def _finish(self, rec: EngineTask) -> None:
        rec.finished_s = self._monotonic()
        dt = max(0.0, rec.finished_s - rec.started_s)
        with self._cv:
            prev = self._ewma_s[rec.klass]
            self._ewma_s[rec.klass] = dt if prev is None \
                else (1.0 - _EWMA_ALPHA) * prev + _EWMA_ALPHA * dt
            self.completed[rec.klass] += 1
        SENSORS.observe("serving_request_seconds", dt,
                        labels={"class": rec.klass.value})

    # -- observation / lifecycle -------------------------------------------
    def queue_depth(self, klass: TaskClass) -> int:
        with self._cv:
            return len(self._queues[klass])

    def service_time_s(self, klass: TaskClass) -> float:
        """EWMA of observed service durations (seeded with a class-typical
        default until real observations arrive) — the admission layer's
        Retry-After basis."""
        with self._cv:
            est = self._ewma_s[klass]
        return est if est is not None else _DEFAULT_SERVICE_S[klass]

    def _retry_after_locked(self, klass: TaskClass, depth: int) -> float:
        est = self._ewma_s[klass]
        if est is None:
            est = _DEFAULT_SERVICE_S[klass]
        workers = max(1, sum(1 for t in self._threads
                             if t.name.startswith(
                                 f"serving-{klass.value.lower()}-")))
        return max(1.0, depth * est / workers)

    def retry_after_s(self, klass: TaskClass, depth: int) -> float:
        """Seconds until ``depth`` queued tasks of this class should have
        drained at the observed service rate."""
        with self._cv:
            return self._retry_after_locked(klass, depth)

    def lifecycle(self, task_id: str) -> str | None:
        with self._cv:
            rec = self._records.get(task_id)
            return rec.lifecycle if rec is not None else None

    def evict(self, task_id: str) -> None:
        """Mark a finished task's record evicted (the UserTaskManager's
        retention dropped its stored result). Unknown ids are a no-op —
        coalesced joiner ids never had their own engine record."""
        with self._cv:
            rec = self._records.get(task_id)
            if rec is None or rec.lifecycle not in ("done", "failed"):
                return
            rec.lifecycle = "evicted"
            self.evicted += 1
        SENSORS.count("serving_tasks_evicted",
                      labels={"class": rec.klass.value})

    def stats(self) -> dict:
        with self._cv:
            return {
                "queued": {k.value: len(q)
                           for k, q in self._queues.items()},
                "completed": {k.value: v
                              for k, v in self.completed.items()},
                "serviceTimeS": {
                    k.value: self._ewma_s[k]
                    if self._ewma_s[k] is not None
                    else _DEFAULT_SERVICE_S[k]
                    for k in TaskClass},
                "evicted": self.evicted,
            }

    def shutdown(self) -> None:
        with self._cv:
            self._shutdown = True
            for q in self._queues.values():
                while q:
                    rec, _fn, fut = q.popleft()
                    rec.lifecycle = "evicted"
                    fut.cancel()
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=5.0)
