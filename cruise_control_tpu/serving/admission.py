"""Queue-depth-aware admission control (round 20 serving front door).

Layered ABOVE the per-cluster circuit breaker: the breaker protects the
fleet from a FAILING cluster, admission protects the front door from an
OVERLOADED one. When a class queue is already past its depth bound, new
work is shed immediately with 429 + Retry-After derived from the
observed per-class service rate (excess depth x EWMA service time) — the
client learns exactly when capacity should exist, and the accepted
requests keep their latency band instead of everyone queueing into
timeout. Polls of existing tasks, response-cache hits, and coalesced
joins are never shed: they consume no solver capacity.
"""

from __future__ import annotations

from ..utils.sensors import SENSORS
from .tasks import TaskClass


class AdmissionShedError(RuntimeError):
    """Maps to HTTP 429 + Retry-After."""

    def __init__(self, klass: TaskClass, depth: int, max_depth: int,
                 retry_after_s: float):
        super().__init__(
            f"{klass.value} queue depth {depth} over admission bound "
            f"{max_depth}; request shed — retry in "
            f"{retry_after_s:.0f}s")
        self.klass = klass
        self.depth = depth
        self.max_depth = max_depth
        self.retry_after_s = retry_after_s


class AdmissionController:
    def __init__(self, viewer_max: int = 32, solver_max: int = 8,
                 enabled: bool = True):
        self.enabled = bool(enabled)
        self._max = {TaskClass.VIEWER: int(viewer_max),
                     TaskClass.SOLVER: int(solver_max)}
        self.shed = {k: 0 for k in TaskClass}

    def max_depth(self, klass: TaskClass) -> int:
        return self._max[klass]

    def admit(self, klass: TaskClass, depth: int,
              service_time_s: float) -> None:
        """Raise AdmissionShedError when the class queue is past its
        bound; otherwise record the depth gauge and admit."""
        SENSORS.gauge("serving_queue_depth", float(depth),
                      labels={"class": klass.value})
        if not self.enabled or depth < self._max[klass]:
            return
        retry = max(1.0,
                    (depth - self._max[klass] + 1) * float(service_time_s))
        self.shed[klass] += 1
        SENSORS.count("serving_requests_shed",
                      labels={"class": klass.value})
        raise AdmissionShedError(klass, depth, self._max[klass], retry)

    def stats(self) -> dict:
        return {"enabled": self.enabled,
                "maxDepth": {k.value: v for k, v in self._max.items()},
                "shed": {k.value: v for k, v in self.shed.items()}}
