"""Deterministic load-test harness (round 20 serving front door).

The digital-twin discipline applied to HTTP: the arrival SCHEDULE is a
pure function of the seed — a crc32-derived open-loop Poisson process
over a mixed request-class profile, generated entirely in virtual time
(wall-clock-free, byte-identical per seed, digestable) — while the
EXECUTION drives the real transport-independent
``CruiseControlApi.handle`` with genuine thread concurrency. Latency is
observed through the injected ``monotonic`` seam (CCSA004: the schedule
never depends on it; only the measured report does, and a measurement IS
machine-dependent by nature — the SLO bands pinned in
bench_baseline.json absorb that).

The report carries everything the SERVING CI row judges: per-class
p50/p99 latency, throughput, shed rate (429s with Retry-After),
response-status histogram, and per-profile-entry body digests for
byte-identity canaries against solo solves.
"""

from __future__ import annotations

import dataclasses
import json
import math
import threading
import time
import zlib
from typing import Callable

_U32 = float(0xFFFFFFFF)

URL_PREFIX = "/kafkacruisecontrol"


def _u01(seed: int, salt: str, n: int) -> float:
    """Uniform [0, 1] from the crc32 counter-mode derivation
    (testing/chaos.py's idiom) — no ``random`` module, no global state."""
    return zlib.crc32(f"{seed}:{salt}:{n}".encode()) / _U32


@dataclasses.dataclass(frozen=True)
class RequestSpec:
    """One profile entry: a concrete request plus its class label and
    sampling weight."""

    name: str
    method: str = "GET"
    path: str = f"{URL_PREFIX}/state"
    query: str = ""
    klass: str = "VIEWER"
    weight: float = 1.0


@dataclasses.dataclass(frozen=True)
class ScheduledRequest:
    seq: int
    at_s: float  # virtual arrival time from schedule start
    spec: RequestSpec


def mixed_profile(cluster_ids=()) -> list[RequestSpec]:
    """The default mixed request-class profile: mostly cheap viewer
    reads, a steady trickle of solver-heavy proposals — per registered
    cluster when ids are given, against the default facade otherwise."""
    suffixes = [f"cluster={cid}" for cid in cluster_ids] or [""]
    out = []
    for sfx in suffixes:
        tag = f":{sfx.split('=', 1)[1]}" if sfx else ""
        amp = "&" if sfx else ""
        out.extend([
            RequestSpec(f"state{tag}", "GET", f"{URL_PREFIX}/state",
                        sfx, "VIEWER", 4.0),
            RequestSpec(f"kafka_cluster_state{tag}", "GET",
                        f"{URL_PREFIX}/kafka_cluster_state", sfx,
                        "VIEWER", 2.0),
            RequestSpec(f"load{tag}", "GET", f"{URL_PREFIX}/load", sfx,
                        "VIEWER", 2.0),
            RequestSpec(f"user_tasks{tag}", "GET",
                        f"{URL_PREFIX}/user_tasks", sfx, "VIEWER", 1.0),
            RequestSpec(f"proposals{tag}", "GET",
                        f"{URL_PREFIX}/proposals", sfx, "SOLVER", 2.0),
            RequestSpec(f"proposals_verbose{tag}", "GET",
                        f"{URL_PREFIX}/proposals",
                        f"{sfx}{amp}verbose=true", "SOLVER", 1.0),
        ])
    return out


def generate_schedule(profile: list[RequestSpec], seed: int = 0,
                      rate_rps: float = 50.0, duration_s: float = 2.0,
                      ) -> list[ScheduledRequest]:
    """Open-loop Poisson arrivals in VIRTUAL time: exponential
    inter-arrival gaps and weighted endpoint picks, both crc32-derived
    from (seed, counter). Same seed ⇒ byte-identical schedule."""
    total_w = sum(s.weight for s in profile)
    if total_w <= 0:
        raise ValueError("profile weights must sum to > 0")
    out: list[ScheduledRequest] = []
    t = 0.0
    n = 0
    while True:
        u = max(_u01(seed, "gap", n), 1e-9)
        t += -math.log(u) / max(rate_rps, 1e-9)
        if t >= duration_s:
            break
        pick = _u01(seed, "pick", n) * total_w
        acc = 0.0
        spec = profile[-1]
        for s in profile:
            acc += s.weight
            if pick < acc:
                spec = s
                break
        out.append(ScheduledRequest(seq=n, at_s=round(t, 9), spec=spec))
        n += 1
    return out


def schedule_digest(schedule: list[ScheduledRequest]) -> str:
    """crc32 of the canonical JSON rendering — the determinism canary
    pinned in bench_baseline.json."""
    rows = [[r.seq, f"{r.at_s:.9f}", r.spec.name, r.spec.method,
             r.spec.path, r.spec.query] for r in schedule]
    payload = json.dumps(rows, separators=(",", ":"))
    return f"{zlib.crc32(payload.encode()):08x}"


def body_digest(body: dict) -> str:
    """crc32 of the sorted-key JSON serialization — byte-identity proxy
    for response-parity canaries."""
    payload = json.dumps(body, sort_keys=True, default=str)
    return f"{zlib.crc32(payload.encode()):08x}"


@dataclasses.dataclass
class RequestResult:
    seq: int
    name: str
    klass: str
    status: int
    latency_s: float
    retry_after: bool
    digest: str


@dataclasses.dataclass
class LoadReport:
    schedule_digest: str
    requests: int
    wall_s: float
    throughput_rps: float
    by_status: dict
    by_class: dict          # klass -> {count, p50_s, p99_s}
    shed: int               # 429 responses
    shed_with_retry_after: int
    shed_rate: float
    digests: dict           # spec name -> set of 200-response digests
    results: list
    # Per-segment latency attribution from the facade's journey ring
    # (serving.journey.segment_attribution): segment -> count/total/
    # p50/p99 plus the attributed-fraction rollup. None when journeys
    # are disabled or no ring was supplied.
    attribution: dict | None = None

    def to_dict(self) -> dict:
        out = {
            "schedule_digest": self.schedule_digest,
            "requests": self.requests,
            "wall_s": round(self.wall_s, 4),
            "throughput_rps": round(self.throughput_rps, 2),
            "by_status": dict(sorted(self.by_status.items())),
            "by_class": self.by_class,
            "shed": self.shed,
            "shed_with_retry_after": self.shed_with_retry_after,
            "shed_rate": round(self.shed_rate, 4),
        }
        if self.attribution is not None:
            out["attribution"] = self.attribution
        return out


def _quantile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


def run_schedule(api, schedule: list[ScheduledRequest],
                 concurrency: int = 8,
                 headers: dict | None = None,
                 monotonic: Callable[[], float] = time.monotonic,
                 journey_log=None) -> LoadReport:
    """Execute the schedule against the REAL api: ``concurrency`` worker
    threads consume requests in arrival ORDER (the open-loop property
    lives in the schedule — arrivals never wait for completions beyond
    the worker bound), each measuring its own wall latency through the
    injected clock seam. Pass the facade's ``journey_log`` to fold its
    per-request segment attribution into the report (where did the wall
    time GO, not just how long it took)."""
    results: list[RequestResult | None] = [None] * len(schedule)
    cursor = [0]
    lock = threading.Lock()
    hdrs = headers or {}

    def worker():
        while True:
            with lock:
                i = cursor[0]
                if i >= len(schedule):
                    return
                cursor[0] = i + 1
            req = schedule[i]
            t0 = monotonic()
            status, body, out_headers = api.handle(
                req.spec.method, req.spec.path, req.spec.query,
                dict(hdrs), "loadgen")
            dt = monotonic() - t0
            results[i] = RequestResult(
                seq=req.seq, name=req.spec.name, klass=req.spec.klass,
                status=int(status), latency_s=dt,
                retry_after="Retry-After" in out_headers,
                digest=body_digest(body) if status == 200 else "")

    t_start = monotonic()
    threads = [threading.Thread(target=worker, name=f"loadgen-{i}",
                                daemon=True)
               for i in range(max(1, concurrency))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = max(monotonic() - t_start, 1e-9)

    done = [r for r in results if r is not None]
    by_status: dict[int, int] = {}
    by_class: dict[str, dict] = {}
    digests: dict[str, set] = {}
    shed = shed_ra = 0
    lat: dict[str, list[float]] = {}
    for r in done:
        by_status[r.status] = by_status.get(r.status, 0) + 1
        lat.setdefault(r.klass, []).append(r.latency_s)
        if r.status == 429:
            shed += 1
            if r.retry_after:
                shed_ra += 1
        if r.status == 200 and r.digest:
            digests.setdefault(r.name, set()).add(r.digest)
    for klass, vals in lat.items():
        vals.sort()
        by_class[klass] = {"count": len(vals),
                           "p50_s": round(_quantile(vals, 0.50), 6),
                           "p99_s": round(_quantile(vals, 0.99), 6)}
    attribution = None
    if journey_log is not None and getattr(journey_log, "enabled", False):
        from .journey import segment_attribution
        attribution = segment_attribution(journey_log.entries())
    return LoadReport(
        schedule_digest=schedule_digest(schedule),
        requests=len(done), wall_s=wall,
        throughput_rps=len(done) / wall,
        by_status=by_status, by_class=by_class,
        shed=shed, shed_with_retry_after=shed_ra,
        shed_rate=shed / max(1, len(done)),
        digests=digests, results=done, attribution=attribution)


def slo_violations(report: LoadReport, slo: dict) -> list[str]:
    """Judge a report against an SLO dict — the canary contract for the
    bench stage. Supported keys: ``max_p99_s`` ({class: seconds}),
    ``min_throughput_rps``, ``max_shed_rate``, ``min_shed`` (overload
    arms must actually shed), ``require_retry_after`` (every 429 carries
    the header), ``max_error_rate`` (non-200/202/429 responses)."""
    flips: list[str] = []
    for klass, bound in (slo.get("max_p99_s") or {}).items():
        got = (report.by_class.get(klass) or {}).get("p99_s", 0.0)
        if got > bound:
            flips.append(f"{klass} p99 {got:.3f}s > SLO {bound:.3f}s")
    min_tp = slo.get("min_throughput_rps")
    if min_tp is not None and report.throughput_rps < min_tp:
        flips.append(f"throughput {report.throughput_rps:.1f} rps < "
                     f"SLO {min_tp:.1f}")
    max_shed = slo.get("max_shed_rate")
    if max_shed is not None and report.shed_rate > max_shed:
        flips.append(f"shed rate {report.shed_rate:.3f} > "
                     f"SLO {max_shed:.3f}")
    min_shed = slo.get("min_shed")
    if min_shed is not None and report.shed < min_shed:
        flips.append(f"only {report.shed} requests shed; overload arm "
                     f"expected >= {min_shed}")
    if slo.get("require_retry_after") and \
            report.shed_with_retry_after < report.shed:
        flips.append(f"{report.shed - report.shed_with_retry_after} "
                     "shed responses missing Retry-After")
    max_err = slo.get("max_error_rate")
    if max_err is not None:
        errors = sum(v for k, v in report.by_status.items()
                     if k not in (200, 202, 429))
        rate = errors / max(1, report.requests)
        if rate > max_err:
            flips.append(f"error rate {rate:.3f} > SLO {max_err:.3f} "
                         f"(statuses {report.by_status})")
    return flips
