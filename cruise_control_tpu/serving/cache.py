"""Model-generation-keyed response cache + coalescing keys (round 20).

The refresh cache's ClusterMeta + metadata generation is the identity of
everything the solver serves: two requests with the same (cluster,
endpoint, canonical params, load-model generation, goal-chain
fingerprint) are answers to the SAME question, and the solver is
deterministic, so the answer may be replayed byte-identical until the
generation or the configured chain moves. The cache stores the final
response envelope (the exact dict ``json.dumps`` serializes), keyed on
that identity — a hit never re-enters the task engine, the admission
layer, or the scheduler.

Honest negative: ``/state`` is NOT generation-pure — executor progress
and anomaly-detector state move without a model-generation bump — so
state caching is opt-in (serving.cache.state.enabled) and documented as
a freshness trade, never a default.
"""

from __future__ import annotations

import collections
import threading

from ..utils.sensors import SENSORS

# Generation-pure endpoints whose whole response is a deterministic
# function of the cache identity. REBALANCE and the broker operations are
# deliberately absent: with dryrun=false they mutate the cluster, and
# even a dry run's purpose is usually a fresh look before acting.
CACHEABLE_ENDPOINTS = frozenset({"PROPOSALS", "COMPARE_FUTURES"})

# Read-only endpoints whose identical concurrent requests may share ONE
# in-flight solve (cross-user coalescing): the cacheable set plus the
# model-build reads.
COALESCIBLE_ENDPOINTS = CACHEABLE_ENDPOINTS | {"LOAD", "PARTITION_LOAD"}

# Parameters that explicitly ask for fresh work (or route to the
# simulator twin): their presence disables caching AND coalescing for
# the request. Every other parameter is part of the canonical key —
# same params, same answer.
CACHE_BUSTING_PARAMS = frozenset({"ignore_proposal_cache", "what_if"})


def canonical_params(endpoint: str, params: dict,
                     allowed=COALESCIBLE_ENDPOINTS) -> tuple | None:
    """Order-independent canonical form of a request's parameters, or
    None when the request must not be cached/coalesced (endpoint not in
    ``allowed``, or a cache-busting parameter present)."""
    if endpoint not in allowed:
        return None
    if any(params.get(k) for k in CACHE_BUSTING_PARAMS):
        return None
    return tuple(sorted((k, repr(v)) for k, v in params.items()))


class ResponseCache:
    """Bounded generation-keyed response store. Keys are full identity
    tuples (cluster, endpoint, canonical params, generation,
    fingerprint); values are the response envelope dicts. Entries for a
    dead generation age out by LRU — they can never be hit again, so no
    TTL machinery is needed."""

    def __init__(self, max_entries: int = 256, enabled: bool = True,
                 cache_state: bool = False):
        self._lock = threading.Lock()
        self._entries: collections.OrderedDict[tuple, dict] = \
            collections.OrderedDict()
        self._max = max(1, int(max_entries))
        self.enabled = bool(enabled)
        self.cache_state = bool(cache_state)
        self.hits = 0
        self.misses = 0

    def get(self, key: tuple | None) -> dict | None:
        if not self.enabled or key is None:
            return None
        endpoint = key[1] if len(key) > 1 else ""
        with self._lock:
            body = self._entries.get(key)
            if body is not None:
                self._entries.move_to_end(key)
                self.hits += 1
            else:
                self.misses += 1
        if body is not None:
            SENSORS.count("serving_cache_hits",
                          labels={"endpoint": str(endpoint)})
        else:
            SENSORS.count("serving_cache_misses",
                          labels={"endpoint": str(endpoint)})
        return body

    def put(self, key: tuple | None, body: dict) -> None:
        if not self.enabled or key is None or not isinstance(body, dict):
            return
        with self._lock:
            self._entries[key] = body
            self._entries.move_to_end(key)
            while len(self._entries) > self._max:
                self._entries.popitem(last=False)

    def invalidate(self) -> None:
        with self._lock:
            self._entries.clear()

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries), "hits": self.hits,
                    "misses": self.misses, "enabled": self.enabled}
