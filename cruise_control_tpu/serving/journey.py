"""Per-request journeys: latency attribution for the serving front door.

The round-20 serving layer reports only machine-shaped END-TO-END
latency: nothing attributes a slow PROPOSALS response to admission vs
queue wait vs model build vs solve vs render. A journey is the ambient
per-request record (the ``sensors.cluster_label`` / heal-ledger
``heal_scope`` ContextVar pattern) opened in ``api.server._dispatch``
and stamped at every stage the request already passes through:

- ``admission`` — the admission-controller verdict,
- ``cache_lookup`` — response-cache identity + probe (hit/miss attr),
- ``queue_wait`` — task-engine queue time, per class (VIEWER/SOLVER),
- ``sched_wait`` — fleet-scheduler wait before the device turn,
- ``model_build`` — monitor cluster-model assembly,
- ``solve`` — the optimizer pass, linked to the flight recorder's
  ``passSeqs`` / warm-start attrs and the ambient heal chain id,
- ``proposal_diff`` / ``render`` — response assembly,
- ``cache_store`` — response-cache fill,

plus a ``coalesce`` note (leader vs follower). Completed journeys land
in a bounded lock-guarded ring per facade, served on
``GET /kafkacruisecontrol/journeys`` and mirrored into the
``journey_segment_seconds{endpoint,segment}`` histograms so the loadgen
report can say WHERE time went — and how much of the wall is
unattributed (reported, never hidden).

Deterministic machinery (CCSA004): every timestamp rides the injected
``monotonic``/``clock`` seams — the digital twin runs journeys on its
sim clock. Off-means-off: ``open()`` on a disabled log returns the
shared ``NO_JOURNEY`` null handle (``recording=False``, every method a
no-op), so observation never changes behavior and the disabled path is
ns-scale (benched as ``journey_noop_overhead``).
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import json
import threading
import time
from typing import Callable

from ..utils.sensors import SENSORS

_AMBIENT: contextvars.ContextVar["Journey | None"] = \
    contextvars.ContextVar("journey_current", default=None)


class _NullSegment:
    """Shared no-op segment scope for the disabled path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set(self, **attrs) -> None:
        pass


_NULL_SEGMENT = _NullSegment()


class _NullJourney:
    """Shared null journey (the heal ledger's ``NO_HEAL`` discipline):
    every stamp site calls through unconditionally; the disabled path
    pays one attribute load and a method call, nothing else."""

    __slots__ = ()
    recording = False

    def now(self) -> float:
        return 0.0

    def add(self, name: str, duration_s: float, **attrs) -> None:
        pass

    def seg(self, name: str, **attrs):
        return _NULL_SEGMENT

    def note(self, **attrs) -> None:
        pass


NO_JOURNEY = _NullJourney()


class _SegmentScope:
    """Times a ``with`` block into one journey segment. ``set()``
    attaches attrs before close (cache hit, verdict, pass ids)."""

    __slots__ = ("_journey", "_name", "_attrs", "_t0")

    def __init__(self, journey: "Journey", name: str, attrs: dict):
        self._journey = journey
        self._name = name
        self._attrs = attrs

    def __enter__(self) -> "_SegmentScope":
        self._t0 = self._journey.now()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is not None:
            self._attrs.setdefault("error", exc_type.__name__)
        self._journey.add(self._name,
                          max(0.0, self._journey.now() - self._t0),
                          **self._attrs)
        return False

    def set(self, **attrs) -> None:
        self._attrs.update(attrs)


class Journey:
    """One request's attribution record. Segments are stamped from
    MULTIPLE threads (HTTP handler, engine worker, fleet worker), so
    appends are lock-guarded; stamps after close are dropped — a
    202-returned request's journey records what happened within its
    dispatch wall, not the solve that finishes after it."""

    recording = True

    __slots__ = ("endpoint", "cluster", "opened_unix_s", "status",
                 "attrs", "segments", "total_s", "unattributed_s",
                 "_t0", "_monotonic", "_lock", "_closed")

    def __init__(self, endpoint: str, cluster: str | None,
                 monotonic: Callable[[], float],
                 clock: Callable[[], float]):
        self.endpoint = endpoint
        self.cluster = cluster
        self.opened_unix_s = clock()
        self.status = "open"
        self.attrs: dict = {}
        self.segments: list[tuple[str, float, dict]] = []
        self.total_s = 0.0
        self.unattributed_s = 0.0
        self._monotonic = monotonic
        self._t0 = monotonic()
        self._lock = threading.Lock()
        self._closed = False

    def now(self) -> float:
        return self._monotonic()

    def add(self, name: str, duration_s: float, **attrs) -> None:
        """Append one already-timed segment (the fleet/engine waits are
        measured across threads and stamped at work start)."""
        with self._lock:
            if self._closed:
                return
            self.segments.append((name, max(0.0, float(duration_s)),
                                  attrs))

    def seg(self, name: str, **attrs) -> _SegmentScope:
        """Context manager timing a block into one segment."""
        return _SegmentScope(self, name, dict(attrs))

    def note(self, **attrs) -> None:
        """Journey-level attributes (coalesce role, outcome, error)."""
        with self._lock:
            if not self._closed:
                self.attrs.update(attrs)

    def _finalize(self, status: str) -> bool:
        with self._lock:
            if self._closed:
                return False
            self._closed = True
            self.status = status
            self.total_s = max(0.0, self._monotonic() - self._t0)
            attributed = sum(d for _n, d, _a in self.segments)
            self.unattributed_s = max(0.0, self.total_s - attributed)
            return True

    def to_dict(self) -> dict:
        with self._lock:
            return {
                "endpoint": self.endpoint,
                "cluster": self.cluster,
                "openedTimeUnixMs": int(self.opened_unix_s * 1000),
                "status": self.status,
                "totalS": round(self.total_s, 6),
                "unattributedS": round(self.unattributed_s, 6),
                "attributes": dict(self.attrs),
                "segments": [
                    {"segment": n, "seconds": round(d, 6), **a}
                    for n, d, a in self.segments],
            }


class JourneyLog:
    """Per-facade bounded ring of completed journeys + the open seam.

    ``open()`` is the ONLY branch point: disabled → ``NO_JOURNEY`` and
    every downstream stamp no-ops. ``close()`` finalizes the record,
    appends it to the ring, and mirrors each segment into the
    ``journey_segment_seconds{endpoint,segment}`` histogram (ambient
    cluster label applies, exactly like every other sensor)."""

    def __init__(self, enabled: bool = True, max_entries: int = 256,
                 monotonic: Callable[[], float] = time.monotonic,
                 clock: Callable[[], float] = time.time):
        self._enabled = bool(enabled)
        self._monotonic = monotonic
        self._clock = clock
        self._lock = threading.Lock()
        self._ring: collections.deque[Journey] = \
            collections.deque(maxlen=max(1, int(max_entries)))
        self.journeys_opened = 0
        self.journeys_closed = 0

    @property
    def enabled(self) -> bool:
        return self._enabled

    def open(self, endpoint: str,
             cluster: str | None = None) -> Journey | _NullJourney:
        if not self._enabled:
            return NO_JOURNEY
        journey = Journey(endpoint, cluster, self._monotonic, self._clock)
        with self._lock:
            self.journeys_opened += 1
        return journey

    def close(self, journey: Journey | _NullJourney,
              status: str = "ok") -> None:
        if not journey.recording:
            return
        if not journey._finalize(status):
            return
        with self._lock:
            self._ring.append(journey)
            self.journeys_closed += 1
        for name, duration_s, _attrs in journey.segments:
            SENSORS.observe("journey_segment_seconds", duration_s,
                            labels={"endpoint": journey.endpoint,
                                    "segment": name})

    # -- export ------------------------------------------------------------
    def entries(self, endpoint: str | None = None,
                limit: int | None = None) -> list[dict]:
        """Completed journeys, newest first, optionally filtered by
        endpoint name."""
        with self._lock:
            snapshot = list(self._ring)
        out: list[dict] = []
        if limit is not None and limit <= 0:
            return out
        for j in reversed(snapshot):
            if endpoint is not None and j.endpoint != endpoint:
                continue
            out.append(j.to_dict())
            if limit is not None and len(out) >= limit:
                break
        return out

    def dump_json(self, path: str) -> int:
        """Write the ring (newest first) as a JSON document — the bench
        stage's ``BENCH_JOURNEY_FILE`` CI artifact."""
        entries = self.entries()
        with open(path, "w") as f:
            json.dump({"numJourneys": len(entries),
                       "journeys": entries}, f, indent=2)
        return len(entries)

    def stats(self) -> dict:
        with self._lock:
            return {"journeysEnabled": self._enabled,
                    "journeysOpened": self.journeys_opened,
                    "journeysClosed": self.journeys_closed,
                    "ringSize": len(self._ring)}


def current_journey() -> Journey | _NullJourney:
    """The ambient journey (``NO_JOURNEY`` outside any request scope):
    deep layers — the monitor's model build, the facade's solve — stamp
    segments with no plumbing, exactly like ``sensors.cluster_label``."""
    journey = _AMBIENT.get()
    return journey if journey is not None else NO_JOURNEY


@contextlib.contextmanager
def journey_scope(journey: Journey | _NullJourney):
    """Establish ``journey`` as the ambient record. ContextVars do NOT
    cross thread pools: the api layer re-enters this scope inside the
    engine-worker closure and again inside fleet-scheduled work (the
    ``cluster_label`` rewrap discipline)."""
    token = _AMBIENT.set(journey if journey.recording else None)
    try:
        yield journey
    finally:
        _AMBIENT.reset(token)


def segment_attribution(entries: list[dict]) -> dict:
    """Aggregate completed journeys into the per-segment attribution
    table the loadgen report carries: per-segment count/total/p50/p99
    plus the attributed-fraction of total wall (unattributed remainder
    REPORTED, not hidden)."""
    per_seg: dict[str, list[float]] = {}
    total = attributed = 0.0
    for e in entries:
        total += e.get("totalS", 0.0)
        for seg in e.get("segments", ()):
            d = float(seg.get("seconds", 0.0))
            attributed += d
            per_seg.setdefault(seg["segment"], []).append(d)
    table = {}
    for name in sorted(per_seg):
        vals = sorted(per_seg[name])
        n = len(vals)
        table[name] = {
            "count": n,
            "total_s": round(sum(vals), 6),
            "p50_s": round(vals[min(n - 1, int(0.50 * n))], 6),
            "p99_s": round(vals[min(n - 1, int(0.99 * n))], 6),
        }
    return {
        "journeys": len(entries),
        "wall_s": round(total, 6),
        "attributed_s": round(attributed, 6),
        "unattributed_s": round(max(0.0, total - attributed), 6),
        "attributed_fraction": round(attributed / total, 4)
        if total > 0 else 0.0,
        "segments": table,
    }
