"""ForecastEngine: the serving wrapper around the batched forecaster.

One engine per facade (the heal-ledger isolation discipline: a fleet's
clusters and an embedded digital twin each forecast their OWN monitor's
history on their own cadence). The engine

1. pulls the monitor's history export seam
   (``LoadMonitor.load_history`` — the last ``forecast.fit.windows``
   stable windows aligned with the current model's partition rows),
2. runs ``forecaster.fit_project_loads`` — ONE jitted program over the
   whole tensor — and
3. builds the PROJECTED cluster model: the current ``ClusterTensors``
   with its load planes replaced by the per-cell horizon peak, plus the
   confidence band and per-broker aggregates ``GET /forecast`` serves.

Off means off: with ``forecast.enabled=false`` ``forecast()`` returns
None after one config read — no model build, no aggregation, no device
work (the bench ``forecast_noop_overhead`` probe measures exactly this
path, the tracing/resilience guard family).

Determinism (CCSA004): the projection is a pure function of the history
tensor; the engine stamps results with the monitor's model GENERATION,
never wall time.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
from typing import Any

import numpy as np

LOG = logging.getLogger(__name__)


@dataclasses.dataclass
class ForecastResult:
    """One forecasting pass: the projected model plus everything the
    serving surface reports. All values derive from the history tensor
    and the model generation — nothing wall-clock, so a pinned-seed twin
    serves byte-identical forecast bodies."""

    generation: int              # monitor model generation fitted at
    horizon_windows: int
    horizon_s: float             # horizon_windows × window_ms / 1000
    windows_used: int
    period_windows: int
    state: Any                   # current ClusterTensors
    meta: Any                    # ClusterMeta
    projected_state: Any         # state with load planes at horizon peak
    band: np.ndarray             # [P, R] residual-RMS confidence band
    trajectory_broker: np.ndarray  # [H, B, R] projected per-broker loads

    def per_broker(self) -> dict:
        """{broker_id: {resource: {current, projected, band}}} — the
        GET /forecast body's core table (projected = horizon peak,
        band = the broker's aggregated residual-RMS uncertainty)."""
        from ..common.resources import Resource
        from ..model.tensors import broker_load
        cur = np.asarray(broker_load(self.state))
        proj = np.asarray(broker_load(self.projected_state))
        band_b = self._broker_band()
        out: dict = {}
        names = [r.name for r in Resource]
        for i, bid in enumerate(self.meta.broker_ids):
            out[int(bid)] = {
                names[r]: {
                    "current": round(float(cur[i, r]), 3),
                    "projected": round(float(proj[i, r]), 3),
                    "band": round(float(band_b[i, r]), 3),
                } for r in range(cur.shape[1])}
        return out

    def _broker_band(self) -> np.ndarray:
        """[B, R] per-broker confidence band: each partition's residual
        band attributed to its leader broker in quadrature (the broker
        load is a sum over its partitions; independent per-series
        residuals add as root-sum-square on that sum)."""
        assignment = np.asarray(self.state.assignment)      # [P, S]
        leader_slot = np.asarray(self.state.leader_slot)    # [P]
        num_b = int(self.state.capacity.shape[0])
        p_idx = np.arange(assignment.shape[0])
        slot = np.clip(leader_slot, 0, assignment.shape[1] - 1)
        leader_broker = assignment[p_idx, slot]
        valid = (leader_slot >= 0) & (leader_broker >= 0) \
            & np.asarray(self.state.partition_mask)
        var = np.zeros((num_b, self.band.shape[1]), dtype=np.float64)
        lb = np.clip(leader_broker, 0, num_b - 1)
        for r in range(self.band.shape[1]):
            np.add.at(var[:, r], lb[valid],
                      np.square(self.band[valid, r], dtype=np.float64))
        return np.sqrt(var)

    def to_dict(self) -> dict:
        return {
            "generation": self.generation,
            "horizonWindows": self.horizon_windows,
            "horizonSeconds": round(self.horizon_s, 3),
            "windowsUsed": self.windows_used,
            "seasonalPeriodWindows": self.period_windows,
            "bandMax": round(float(self.band.max()), 4)
            if self.band.size else 0.0,
            "perBroker": self.per_broker(),
        }


class ForecastEngine:
    """Config-gated forecaster for one facade. ``forecast()`` is
    generation-cached: re-forecasting an unchanged monitor generation
    returns the cached result (the detector runs every interval; the
    fit only re-runs when new windows landed)."""

    def __init__(self, config, load_monitor):
        self._config = config
        self._monitor = load_monitor
        self._lock = threading.Lock()
        self._last: ForecastResult | None = None

    @property
    def enabled(self) -> bool:
        return self._config.get_boolean("forecast.enabled")

    @property
    def last_result(self) -> ForecastResult | None:
        # Lock-FREE read of the published result (atomic reference
        # swap): the cached GET /forecast path must stay inline even
        # while a fit — possibly a first-shape XLA compile — holds the
        # single-flight lock.
        return self._last

    def forecast(self) -> ForecastResult | None:
        """Fit + project the current history; None when disabled or the
        monitor has fewer than ``forecast.fit.windows`` stable windows."""
        if not self.enabled:
            return None
        from ..utils.sensors import SENSORS
        from ..utils.tracing import TRACER
        fit_windows = self._config.get_int("forecast.fit.windows")
        # The whole fit runs UNDER the lock (single-flight): the
        # detector tick, a /forecast?refresh=true request, and a futures
        # worker can all arrive for the same uncached generation — one
        # fit serves them all instead of three byte-identical model
        # builds + device programs racing last-writer-wins.
        with self._lock:
            gen = self._monitor.model_generation
            if self._last is not None and self._last.generation == gen:
                return self._last
            exported = self._monitor.load_history(fit_windows)
            if exported is None:
                SENSORS.count("forecast_skipped_not_ready")
                return None
            history, window_ms, state, meta = exported
            horizon = self._config.get_int("forecast.horizon.windows")
            period = self._config.get_int(
                "forecast.seasonal.period.windows")
            with TRACER.span("forecast.fit", windows=fit_windows,
                             horizon=horizon,
                             partitions=int(state.num_partitions)):
                import jax.numpy as jnp

                from .forecaster import fit_project_loads
                peak_l, peak_f, band, traj = fit_project_loads(
                    jnp.asarray(history), state.leader_load,
                    state.follower_load, horizon, period)
                projected = dataclasses.replace(
                    state, leader_load=jnp.asarray(peak_l),
                    follower_load=jnp.asarray(peak_f))
                traj_broker = self._broker_trajectory(
                    state, np.asarray(traj))
            result = ForecastResult(
                generation=gen, horizon_windows=horizon,
                horizon_s=horizon * window_ms / 1000.0,
                windows_used=fit_windows, period_windows=period,
                state=state, meta=meta, projected_state=projected,
                band=np.asarray(band), trajectory_broker=traj_broker)
            self._last = result
        SENSORS.count("forecast_runs")
        SENSORS.gauge("forecast_windows_used", fit_windows)
        return result

    @staticmethod
    def _broker_trajectory(state, trajectory: np.ndarray) -> np.ndarray:
        """[H, B, R] projected per-broker LEADER loads per horizon window
        (the /forecast sparkline view): attribute each partition row's
        projected leader load to its leader broker."""
        import numpy as _np
        assignment = _np.asarray(state.assignment)      # [P, S]
        leader_slot = _np.asarray(state.leader_slot)    # [P]
        num_b = int(state.capacity.shape[0])
        p_idx = _np.arange(assignment.shape[0])
        slot = _np.clip(leader_slot, 0, assignment.shape[1] - 1)
        leader_broker = assignment[p_idx, slot]
        valid = (leader_slot >= 0) & (leader_broker >= 0) \
            & _np.asarray(state.partition_mask)
        out = _np.zeros((trajectory.shape[0], num_b, trajectory.shape[2]),
                        dtype=_np.float32)
        lb = _np.clip(leader_broker, 0, num_b - 1)
        for h in range(trajectory.shape[0]):
            for r in range(trajectory.shape[2]):
                _np.add.at(out[h, :, r], lb[valid],
                           trajectory[h, valid, r])
        return out
