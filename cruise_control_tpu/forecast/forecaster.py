"""Batched per-partition load forecaster: one jitted fit+projection.

Model: per (partition, resource) series ``y[w]`` over the last ``W``
stable windows, fit a small linear basis by least squares —

    y(t) ≈ b0 + b1·t  (+ b2·sin(2πt/T) + b3·cos(2πt/T) when a seasonal
                        period ``T`` is configured)

— and project it ``H`` windows past the last observation. The fit is a
closed-form normal-equations solve shared across every series (one
``[K, K]`` Gram matrix for the whole tensor), vmapped over the flattened
``partitions × resources`` series axis, so the WHOLE history tensor fits
and projects in ONE jitted device program: no per-partition host loop,
and the jit cache holds exactly one entry per (W, P, R, H, T) shape
(pinned in tests/test_forecast.py via the ``_cache_size`` counter, the
same discipline as the megabatch/warmstart rounds).

The confidence band is the per-series residual RMS — honest about what a
4-basis fit can promise: it widens exactly where the history refuses to
be a trend + one sinusoid. Projections are clamped at zero (loads are
non-negative) and the violation-scoring view takes the per-cell PEAK
over the horizon, so one goal-stats program answers "does any window
within H violate?" conservatively.

Determinism (CCSA004): pure functions of the history tensor — no wall
clock, no randomness; same history bytes ⇒ same projection bytes.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp

#: Ridge term on the Gram diagonal: the basis columns are well scaled
#: (t normalized to [0, 1]) so this only guards the degenerate
#: constant-history case from a singular solve.
_RIDGE = 1e-6


def _basis(t: jax.Array, num_windows: int, period: int) -> jax.Array:
    """[len(t), K] design matrix. ``t`` is the window index (0 = oldest
    fitted window); the trend column is normalized by the fit span so
    coefficients stay O(data) regardless of W."""
    span = max(1, num_windows - 1)
    cols = [jnp.ones_like(t), t / span]
    if period > 0:
        w = 2.0 * math.pi / period
        cols += [jnp.sin(w * t), jnp.cos(w * t)]
    return jnp.stack(cols, axis=1)


@partial(jax.jit, static_argnames=("horizon", "period"))
def project_series(history: jax.Array, horizon: int, period: int,
                   ) -> tuple[jax.Array, jax.Array]:
    """Fit + project every series of ``history [W, S]`` in one program.

    Returns ``(projected [H, S], sigma [S])`` — the per-window
    projections for the next ``horizon`` windows and the per-series
    residual RMS of the fit. ``period`` (windows) adds the seasonal
    pair to the basis; 0 = trend-only.
    """
    num_windows = history.shape[0]
    t_fit = jnp.arange(num_windows, dtype=jnp.float32)
    t_proj = num_windows - 1 + jnp.arange(1, horizon + 1, dtype=jnp.float32)
    x_fit = _basis(t_fit, num_windows, period)            # [W, K]
    x_proj = _basis(t_proj, num_windows, period)          # [H, K]
    gram = x_fit.T @ x_fit + _RIDGE * jnp.eye(x_fit.shape[1],
                                              dtype=jnp.float32)

    def fit_one(y):
        beta = jnp.linalg.solve(gram, x_fit.T @ y)        # [K]
        resid = y - x_fit @ beta
        sigma = jnp.sqrt(jnp.mean(resid * resid))
        return x_proj @ beta, sigma

    # vmapped over the flattened series axis: the whole tensor fits in
    # one batched program (out axis 1 keeps [H, S] layout).
    proj, sigma = jax.vmap(fit_one, in_axes=1, out_axes=(1, 0))(history)
    return jnp.maximum(proj, 0.0), sigma


@partial(jax.jit, static_argnames=("horizon", "period"))
def fit_project_loads(history: jax.Array, cur_leader: jax.Array,
                      cur_follower: jax.Array, horizon: int, period: int,
                      avg_resource: jax.Array | None = None,
                      ) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """The full forecasting program over the history tensor.

    ``history [W, P, R]`` is the leader-load view of the last W stable
    windows (monitor history export seam); ``cur_leader``/``cur_follower``
    ``[P, R]`` are the CURRENT model's load planes. Returns

    - ``peak_leader [P, R]``: per-cell PEAK projected MODEL-VIEW leader
      load over the horizon (the conservative violation-scoring view),
    - ``peak_follower [P, R]``: the current follower plane scaled by the
      same per-cell projection ratio (follower load tracks its leader's
      ingest; the ratio keeps the model's CPU-estimation relationship
      rather than refitting a second tensor),
    - ``band [P, R]``: the residual-RMS confidence band,
    - ``trajectory [H, P, R]``: the per-window MODEL-VIEW projections
      (served on GET /forecast).

    MODEL VIEW: the cluster model reduces AVG-strategy resources (CPU,
    NW_IN, NW_OUT) by the MEAN over its retained windows, so what the
    detector will see in ``h`` windows is the rolling mean of the last
    ``W`` windows at that point — ``mean(history[h:] ∪ proj[:h])`` —
    not the raw window value. Scoring the raw projection would predict
    violations the lagging model never reports (phantom predictions
    that can only miss). LATEST-strategy resources (DISK) take the raw
    projected window. ``avg_resource [R]`` bool marks the AVG columns
    (defaults to the Kafka metric-def layout: all but DISK).

    One jitted program end to end — fit, projection, the rolling-mean
    model view, peak reduction, and the follower scaling all trace into
    a single XLA executable.
    """
    num_w, num_p, num_r = history.shape
    flat = history.reshape(num_w, num_p * num_r)
    proj, sigma = project_series(flat, horizon, period)
    raw = proj.reshape(horizon, num_p, num_r)
    band = sigma.reshape(num_p, num_r)
    if avg_resource is None:
        from ..common.resources import Resource
        avg_resource = jnp.asarray(
            [r is not Resource.DISK for r in Resource])
    # Rolling model mean at horizon h (1-indexed) over a W-window span:
    # (sum(history[h:]) + sum(raw[max(0, h-W):h])) / W.
    hs = jnp.cumsum(history[::-1], axis=0)[::-1]   # hs[k] = Σ history[k:]
    pp = jnp.concatenate([jnp.zeros((1, num_p, num_r), raw.dtype),
                          jnp.cumsum(raw, axis=0)])  # pp[k] = Σ raw[:k]
    h_idx = jnp.arange(1, horizon + 1)
    suffix = jnp.where((h_idx < num_w)[:, None, None],
                       hs[jnp.clip(h_idx, 0, num_w - 1)], 0.0)
    proj_part = pp[h_idx] - pp[jnp.maximum(0, h_idx - num_w)]
    rolled = (suffix + proj_part) / float(num_w)
    trajectory = jnp.where(avg_resource[None, None, :], rolled, raw)
    peak_leader = jnp.max(trajectory, axis=0)
    # Follower plane: scale by the projected/current ratio where the
    # current leader load is meaningful; keep the current value where it
    # is ~zero (idle partitions stay idle rather than exploding on a
    # 0/0 ratio).
    safe = jnp.where(cur_leader > 1e-9, cur_leader, 1.0)
    ratio = jnp.where(cur_leader > 1e-9, peak_leader / safe, 1.0)
    peak_follower = jnp.maximum(cur_follower * ratio, 0.0)
    return peak_leader, peak_follower, band, trajectory
