"""Predictive rebalancing (round 19): batched on-device load forecasting.

The reference system is purely reactive — windowed MetricSampleAggregator
history in, anomaly detection out (PAPER.md §Monitor/Core) — so every
heal starts after the SLO is already broken. The windowed history is
already device-resident here; this package closes ROADMAP item 6:

- ``forecaster``: a seasonal-trend least-squares fit + projection over
  the FULL ``[windows, partitions, resources]`` history tensor, vmapped
  over the flattened series axis inside ONE jitted program (no
  per-partition host loops; pinned via the jit-cache counter).
- ``engine``: the serving wrapper — pulls the monitor's history export
  seam, runs the fit, and builds the PROJECTED cluster model (peak load
  over the horizon, per partition and resource, with a residual-std
  confidence band) that ``detector/predictive.py`` scores through the
  existing batched goal-stats program.

Determinism: both modules sit under CCSA004's deterministic contract —
the projection feeds solver inputs and anomaly decisions, so no wall
clock and no global randomness anywhere on the fit path.
"""

from .engine import ForecastEngine, ForecastResult
from .forecaster import fit_project_loads, project_series

__all__ = ["ForecastEngine", "ForecastResult", "fit_project_loads",
           "project_series"]
