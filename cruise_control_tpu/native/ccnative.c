/* cruise-control-tpu native runtime: the ingest data-path hot loops.
 *
 * The framework's TPU compute path is JAX/XLA; this library is the native
 * runtime AROUND it — the byte-level work that sits between the Kafka wire
 * protocol and the device-resident load tensors, where a Python per-record
 * loop is the bottleneck at 7k-broker scale (millions of metric records
 * per sampling interval):
 *
 *   - cc_crc32c:         CRC-32C (Castagnoli), the record-batch v2
 *                        checksum (KIP-98).
 *   - cc_count_records:  total record count over a concatenation of
 *                        record batches (a fetch response's record set).
 *   - cc_index_records:  one-pass varint parse of every record into a
 *                        fixed-width int64 index table that Python / numpy
 *                        consumes zero-copy (offset, timestamp, key/value
 *                        spans, header span).
 *
 * Format reference: kafka/wire/records.py (the pure-Python serde this
 * accelerates — byte-for-byte the same record-batch v2 layout, magic 2,
 * zigzag varints); semantics cross-checked by tests/test_native.py, which
 * fuzzes both decoders against each other.
 */

#include <stddef.h>
#include <stdint.h>

/* ---- CRC-32C ---------------------------------------------------------- */

static uint32_t crc_table[256];
static int crc_init_done = 0;

static void crc_init(void) {
    for (uint32_t n = 0; n < 256; n++) {
        uint32_t c = n;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? (c >> 1) ^ 0x82F63B78u : c >> 1;
        crc_table[n] = c;
    }
    crc_init_done = 1;
}

uint32_t cc_crc32c(uint32_t crc, const unsigned char *buf, size_t len) {
    if (!crc_init_done) crc_init();
    crc = ~crc;
    for (size_t i = 0; i < len; i++)
        crc = crc_table[(crc ^ buf[i]) & 0xFF] ^ (crc >> 8);
    return ~crc;
}

/* ---- record batch v2 parsing ----------------------------------------- */

#define CC_ERR_MAGIC       (-2)  /* unsupported record-batch magic       */
#define CC_ERR_CRC         (-3)  /* batch CRC mismatch                   */
#define CC_ERR_COMPRESSION (-4)  /* compressed batch (unsupported)       */
#define CC_ERR_MALFORMED   (-5)  /* truncated/inconsistent record data   */
#define CC_ERR_CAPACITY    (-6)  /* output table too small               */

/* Batch layout constants (records.py: _HEADER_FMT ">qiibIhiqqqhii").     */
#define BATCH_CRC_OFF   17  /* baseOffset(8) + batchLength(4) + epoch(4) + magic(1) */
#define BATCH_AFTER_CRC 21
#define AFTER_BASE_TS    6  /* attrs(2) + lastOffsetDelta(4)             */
#define AFTER_COUNT     36  /* ... + ts(8+8) + pid(8) + epoch(2) + seq(4) */
#define AFTER_RECORDS   40
#define MIN_BATCH_LEN   49  /* epoch+magic+crc + the 40-byte after-crc head */

static uint32_t rd32be(const unsigned char *p) {
    return ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16)
         | ((uint32_t)p[2] << 8) | p[3];
}

static int64_t rd64be(const unsigned char *p) {
    uint64_t v = 0;
    for (int i = 0; i < 8; i++) v = (v << 8) | p[i];
    return (int64_t)v;
}

/* Zigzag varint bounded by `limit`; 0 on success. */
static int read_varint(const unsigned char *p, size_t limit, size_t *pos,
                       int64_t *out) {
    uint64_t v = 0;
    int shift = 0;
    while (*pos < limit && shift < 64) {
        unsigned char b = p[(*pos)++];
        v |= (uint64_t)(b & 0x7F) << shift;
        if (!(b & 0x80)) {
            *out = (int64_t)(v >> 1) ^ -(int64_t)(v & 1);
            return 0;
        }
        shift += 7;
    }
    return -1;
}

/* Total records across all COMPLETE batches in buf (a trailing partial
 * batch is ignored, matching client semantics). Negative = error code. */
int64_t cc_count_records(const unsigned char *buf, size_t len) {
    size_t pos = 0;
    int64_t total = 0;
    while (pos + 12 <= len) {
        int32_t batch_len = (int32_t)rd32be(buf + pos + 8);
        /* Drop a trailing PARTIAL batch before validating its fields: a
         * fragment's batchLength bytes may be garbage, and the Python
         * fallback breaks on end > len first — the two decoders must
         * agree on every input (ADVICE r3). Signed end arithmetic so a
         * negative batch_len cannot wrap the unsigned sum. */
        int64_t end64 = (int64_t)pos + 12 + (int64_t)batch_len;
        if (batch_len >= 0 && end64 > (int64_t)len) break;
        if (batch_len < MIN_BATCH_LEN) return CC_ERR_MALFORMED;
        size_t end = (size_t)end64;
        if (buf[pos + 16] != 2) return CC_ERR_MAGIC;
        int32_t count = (int32_t)rd32be(buf + pos + BATCH_AFTER_CRC + AFTER_COUNT);
        /* A record is at least 7 bytes (length varint + attrs + 3 varints
         * + 2 null fields); a forged count larger than the batch's record
         * region could hold must be rejected HERE, not after the caller
         * allocates a count-sized output table (memory-exhaustion
         * hardening). Record region = batch_len minus epoch/magic/crc (9)
         * and the 40-byte after-crc head = batch_len - MIN_BATCH_LEN. */
        int64_t max_records = ((int64_t)batch_len - MIN_BATCH_LEN) / 7;
        if (count < 0 || (int64_t)count > max_records) return CC_ERR_MALFORMED;
        total += count;
        pos = end;
    }
    return total;
}

/* Parse every record into `out` (cap entries of 8 int64 each):
 *   [0] absolute offset        [1] timestamp ms
 *   [2] key byte-offset (-1 = null key)   [3] key length  (-1 = null)
 *   [4] value byte-offset (-1 = null)     [5] value length (-1 = null)
 *   [6] headers byte-offset               [7] header count
 * Byte offsets are absolute into `buf`. Returns the record count or a
 * negative error code. */
int64_t cc_index_records(const unsigned char *buf, size_t len, int verify_crc,
                         int64_t *out, int64_t cap) {
    size_t pos = 0;
    int64_t n = 0;
    while (pos + 12 <= len) {
        int64_t base = rd64be(buf + pos);
        int32_t batch_len = (int32_t)rd32be(buf + pos + 8);
        /* Partial-trailing-batch drop BEFORE field validation (see
         * cc_count_records). */
        int64_t end64 = (int64_t)pos + 12 + (int64_t)batch_len;
        if (batch_len >= 0 && end64 > (int64_t)len) break;
        if (batch_len < MIN_BATCH_LEN) return CC_ERR_MALFORMED;
        size_t end = (size_t)end64;
        if (buf[pos + 16] != 2) return CC_ERR_MAGIC;
        uint32_t crc = rd32be(buf + pos + BATCH_CRC_OFF);
        const unsigned char *after = buf + pos + BATCH_AFTER_CRC;
        size_t alen = end - (pos + BATCH_AFTER_CRC);
        if (verify_crc && cc_crc32c(0, after, alen) != crc) return CC_ERR_CRC;
        int16_t attrs = (int16_t)(((uint16_t)after[0] << 8) | after[1]);
        if (attrs & 0x07) return CC_ERR_COMPRESSION;
        int64_t base_ts = rd64be(after + AFTER_BASE_TS);
        int32_t count = (int32_t)rd32be(after + AFTER_COUNT);
        if (count < 0) return CC_ERR_MALFORMED;
        size_t rpos = AFTER_RECORDS;
        for (int32_t i = 0; i < count; i++) {
            if (n >= cap) return CC_ERR_CAPACITY;
            int64_t rec_len, ts_delta, off_delta, klen, vlen, hcount;
            if (read_varint(after, alen, &rpos, &rec_len)) return CC_ERR_MALFORMED;
            if (rec_len < 1 || rpos + (size_t)rec_len > alen) return CC_ERR_MALFORMED;
            size_t rend = rpos + (size_t)rec_len;
            rpos += 1;  /* record attributes */
            if (read_varint(after, rend, &rpos, &ts_delta)) return CC_ERR_MALFORMED;
            if (read_varint(after, rend, &rpos, &off_delta)) return CC_ERR_MALFORMED;
            if (read_varint(after, rend, &rpos, &klen)) return CC_ERR_MALFORMED;
            int64_t koff = -1;
            if (klen >= 0) {
                if (rpos + (size_t)klen > rend) return CC_ERR_MALFORMED;
                koff = (int64_t)(pos + BATCH_AFTER_CRC + rpos);
                rpos += (size_t)klen;
            } else {
                klen = -1;
            }
            if (read_varint(after, rend, &rpos, &vlen)) return CC_ERR_MALFORMED;
            int64_t voff = -1;
            if (vlen >= 0) {
                if (rpos + (size_t)vlen > rend) return CC_ERR_MALFORMED;
                voff = (int64_t)(pos + BATCH_AFTER_CRC + rpos);
                rpos += (size_t)vlen;
            } else {
                vlen = -1;
            }
            if (read_varint(after, rend, &rpos, &hcount)) return CC_ERR_MALFORMED;
            if (hcount < 0) return CC_ERR_MALFORMED;
            int64_t hoff = (int64_t)(pos + BATCH_AFTER_CRC + rpos);
            for (int64_t h = 0; h < hcount; h++) {
                int64_t hk, hv;
                if (read_varint(after, rend, &rpos, &hk)) return CC_ERR_MALFORMED;
                if (hk < 0 || rpos + (size_t)hk > rend) return CC_ERR_MALFORMED;
                rpos += (size_t)hk;
                if (read_varint(after, rend, &rpos, &hv)) return CC_ERR_MALFORMED;
                if (hv >= 0) {
                    if (rpos + (size_t)hv > rend) return CC_ERR_MALFORMED;
                    rpos += (size_t)hv;
                }
            }
            if (rpos != rend) return CC_ERR_MALFORMED;
            int64_t *e = out + n * 8;
            e[0] = base + off_delta;
            e[1] = base_ts + ts_delta;
            e[2] = koff;
            e[3] = klen;
            e[4] = voff;
            e[5] = vlen;
            e[6] = hoff;
            e[7] = hcount;
            n++;
        }
        pos = end;
    }
    return n;
}
