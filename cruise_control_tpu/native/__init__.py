"""Native runtime loader: compile-on-first-use C hot paths.

``ccnative.c`` holds the ingest data-path loops (CRC-32C, record-batch
index parsing — see the C file's header comment). The library is built
with the system compiler into a per-user 0700 cache directory keyed by a
hash of the source, so editing the C file transparently rebuilds, and a
missing compiler degrades to the pure-Python fallbacks in callers (every
native entry point has one; tests fuzz them against each other).

This keeps the package pip-free (no setuptools build step in this image)
while still shipping real native code where the reference's runtime work
is hottest — the pattern a packaged release would move into a normal
C-extension build.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import tempfile

LOG = logging.getLogger(__name__)

_SRC_PATH = os.path.join(os.path.dirname(__file__), "ccnative.c")

# cc_index_records error codes (keep in sync with ccnative.c).
ERR_MAGIC = -2
ERR_CRC = -3
ERR_COMPRESSION = -4
ERR_MALFORMED = -5
ERR_CAPACITY = -6

_lib = None
_lib_tried = False


def _cache_dir() -> str:
    """Per-user 0700 cache, ownership-verified before any dlopen: a
    world-writable shared path would let another local user plant a
    malicious .so under the predictable name."""
    cache = os.path.join(tempfile.gettempdir(),
                         f"cc_tpu_native_{os.getuid()}")
    os.makedirs(cache, mode=0o700, exist_ok=True)
    st = os.stat(cache)
    if st.st_uid != os.getuid() or st.st_mode & 0o022:
        cache = tempfile.mkdtemp(prefix="cc_tpu_native_")
    return cache


def lib() -> ctypes.CDLL | None:
    """The compiled native library, or None when unavailable (no compiler,
    read-only tmp, ...). Cached per interpreter."""
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    try:
        with open(_SRC_PATH, "rb") as f:
            src = f.read()
        tag = hashlib.sha256(src).hexdigest()[:16]
        cache = _cache_dir()
        so_path = os.path.join(cache, f"libccnative_{tag}.so")
        if not os.path.exists(so_path):
            tmp = so_path + f".build{os.getpid()}"
            subprocess.run(["cc", "-O3", "-shared", "-fPIC", "-o", tmp,
                            _SRC_PATH],
                           check=True, capture_output=True, timeout=120)
            os.replace(tmp, so_path)  # atomic vs concurrent builders
        handle = ctypes.CDLL(so_path)
        handle.cc_crc32c.restype = ctypes.c_uint32
        handle.cc_crc32c.argtypes = [ctypes.c_uint32, ctypes.c_char_p,
                                     ctypes.c_size_t]
        handle.cc_count_records.restype = ctypes.c_int64
        handle.cc_count_records.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        handle.cc_index_records.restype = ctypes.c_int64
        handle.cc_index_records.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64]
        _lib = handle
    except Exception:  # noqa: BLE001 — optional acceleration only
        LOG.debug("native library unavailable; using pure-Python fallbacks",
                  exc_info=True)
        _lib = None
    return _lib


def index_records(data: bytes, verify_crc: bool = True):
    """(index ndarray [N, 8] int64, data) via the native parser, or None
    when the library is unavailable. Raises ValueError on malformed input
    (same failure classes as the Python decoder). Column layout:
    offset, timestamp_ms, key_off, key_len, val_off, val_len,
    headers_off, n_headers; spans are absolute into ``data``; -1 offset or
    length = null field."""
    handle = lib()
    if handle is None:
        return None
    try:
        import numpy as np
    except ImportError:
        # Contract: native entry points degrade to the pure-Python
        # fallback whenever ANY native dependency is missing.
        return None

    n = handle.cc_count_records(data, len(data))
    if n < 0:
        _raise(int(n))
    idx = np.empty((int(n), 8), dtype=np.int64)
    got = handle.cc_index_records(
        data, len(data), int(verify_crc),
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), int(n))
    if got < 0:
        _raise(int(got))
    return idx[:int(got)]


def _raise(code: int) -> None:
    if code == ERR_MAGIC:
        raise ValueError("unsupported record-batch magic")
    if code == ERR_CRC:
        raise ValueError("record batch CRC mismatch")
    if code == ERR_COMPRESSION:
        raise ValueError("unsupported compression codec")
    raise ValueError(f"malformed record batch (native error {code})")
