"""The batched rebalance search.

TPU-native replacement for the reference's greedy inner loop
(AbstractGoal.java:82-135 optimize → rebalanceForBroker → one
maybeApplyBalancingAction at a time). Each round, ONE fused kernel:

1. recomputes derived per-broker state,
2. generates a top-k × top-k grid of candidate actions for the active goal,
3. evaluates the active goal's improvement AND every previously-optimized
   goal's acceptance for all candidates (the lexicographic-constraint stack
   of SURVEY.md §A.3 as boolean masks),
4. picks a conflict-free batch of the best improving candidates
   (scatter-min rank dedup over partition/src/dst), and
5. applies them functionally.

The host loop only reads back one scalar ("moves applied") per round.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp

from ..model.tensors import ClusterTensors, offline_replicas
from .candidates import KIND_MOVE, compute_deltas, generate_candidates
from .constraint import BalancingConstraint
from .derived import DerivedState, compute_derived
from .goals.base import Goal

_EPS_IMPROVEMENT = 1e-9
_OFFLINE_BONUS = 1e12


class OptimizationFailureError(RuntimeError):
    """A hard goal could not be satisfied
    (OptimizationFailureException equivalent)."""


@dataclasses.dataclass(frozen=True)
class SearchConfig:
    num_sources: int = 64
    num_dests: int = 32
    moves_per_round: int = 32
    max_rounds: int = 200


@partial(jax.tree_util.register_dataclass,
         data_fields=["excluded_topics", "excluded_replica_move_brokers",
                      "excluded_leadership_brokers"],
         meta_fields=[])
@dataclasses.dataclass(frozen=True)
class ExclusionMasks:
    """Traced boolean masks built from OptimizationOptions by the optimizer."""

    excluded_topics: jax.Array | None = None            # [T] bool
    excluded_replica_move_brokers: jax.Array | None = None  # [B] bool
    excluded_leadership_brokers: jax.Array | None = None    # [B] bool


def _conflict_free_top_m(score: jax.Array, partition: jax.Array,
                         src: jax.Array, dst: jax.Array, m: int,
                         num_partitions: int, num_brokers: int):
    """Indices of up to ``m`` best-scoring candidates such that no two share
    a partition, source broker, or destination broker. Scatter-min of the
    score-rank per key resolves conflicts in parallel (no sequential scan)."""
    k = min(m, score.shape[0])
    top_score, top_idx = jax.lax.top_k(score, k)
    ok = top_score > _EPS_IMPROVEMENT
    rank = jnp.arange(k, dtype=jnp.int32)

    sel_p = partition[top_idx]
    sel_src = src[top_idx]
    sel_dst = dst[top_idx]

    big = jnp.int32(k + 1)
    rank_eff = jnp.where(ok, rank, big)

    first_p = jnp.full(num_partitions, big, dtype=jnp.int32).at[sel_p].min(rank_eff)
    first_src = jnp.full(num_brokers, big, dtype=jnp.int32).at[sel_src].min(rank_eff)
    first_dst = jnp.full(num_brokers, big, dtype=jnp.int32).at[sel_dst].min(rank_eff)

    accept = ok & (first_p[sel_p] == rank) & (first_src[sel_src] == rank) \
        & (first_dst[sel_dst] == rank)
    return top_idx, accept


@partial(jax.jit, static_argnames=("goal", "optimized", "constraint", "cfg",
                                   "num_topics"))
def optimize_round(state: ClusterTensors, goal: Goal,
                   optimized: tuple[Goal, ...], constraint: BalancingConstraint,
                   cfg: SearchConfig, num_topics: int,
                   masks: ExclusionMasks) -> tuple[ClusterTensors, jax.Array]:
    """One fused search round for ``goal``. Returns (new_state, num_applied)."""
    derived = compute_derived(state, masks.excluded_topics,
                              masks.excluded_replica_move_brokers,
                              masks.excluded_leadership_brokers)
    aux = goal.prepare(state, derived, constraint, num_topics)
    aux_by_goal = {g.name: g.prepare(state, derived, constraint, num_topics)
                   for g in optimized}

    src_score = goal.source_score(state, derived, constraint, aux)
    dst_score = goal.dest_score(state, derived, constraint, aux)
    weight = goal.replica_weight(state, derived, constraint, aux)

    # Self-healing has priority: replicas stranded on dead brokers are
    # always sources with maximal weight, and moving one scores a large
    # bonus so it wins over pure balance refinements
    # (ClusterModel.selfHealingEligibleReplicas / _fixOfflineReplicasOnly).
    off = offline_replicas(state)  # [P, S]
    b = state.num_brokers
    seg = jnp.where(state.assignment >= 0, state.assignment, b).reshape(-1)
    offline_per_broker = jax.ops.segment_sum(
        off.astype(jnp.float32).reshape(-1), seg, num_segments=b + 1)[:b]
    if not goal.leadership_only:
        src_score = src_score + offline_per_broker
        weight = jnp.where(off, 1e30, weight)  # finite: top-k validity uses isfinite

    cand, layout = generate_candidates(state, derived, src_score, dst_score, weight,
                                       cfg.num_sources, cfg.num_dests,
                                       goal.include_leadership, goal.leadership_only)
    deltas = compute_deltas(state, derived, cand)

    accept = deltas.valid
    for g in optimized:
        accept &= g.acceptance(state, derived, constraint,
                               aux_by_goal[g.name], deltas)

    moving_offline = off[deltas.partition, deltas.src_slot] & (deltas.replica_delta > 0)
    imp = goal.improvement(state, derived, constraint, aux, deltas)
    imp = jnp.where(moving_offline & jnp.isfinite(imp) & deltas.valid,
                    jnp.maximum(imp, 0.0) + _OFFLINE_BONUS, imp)
    score = jnp.where(accept, imp, -jnp.inf)

    # Per-source best-destination reduction: each [rows × cols] grid block
    # collapses to one candidate per source replica. Without this, equal
    # scores cluster one partition's candidates at the head of the global
    # sort and the conflict dedup throws most of the round away. A tiny
    # deterministic jitter spreads tied argmaxes across destinations.
    red_parts = []
    offset = 0
    for rows, cols in layout:
        block = score[offset:offset + rows * cols].reshape(rows, cols)
        col_ids = jnp.arange(cols, dtype=jnp.float32)[None, :]
        row_ids = jnp.arange(rows, dtype=jnp.float32)[:, None]
        jitter = ((row_ids * 37.0 + col_ids * 11.0) % 97.0) * 1e-7
        best_col = jnp.argmax(jnp.where(jnp.isfinite(block), block + jitter,
                                        -jnp.inf), axis=1)
        red_parts.append(offset + jnp.arange(rows) * cols + best_col)
        offset += rows * cols
    red_idx = jnp.concatenate(red_parts)

    top_idx_red, sel = _conflict_free_top_m(
        score[red_idx], deltas.partition[red_idx], deltas.src_broker[red_idx],
        deltas.dst_broker[red_idx], cfg.moves_per_round, state.num_partitions,
        state.num_brokers)
    top_idx = red_idx[top_idx_red]

    sel_p = deltas.partition[top_idx]
    sel_slot = deltas.src_slot[top_idx]
    sel_dst_b = deltas.dst_broker[top_idx]
    sel_kind = cand.kind[top_idx]
    sel_dst_slot = cand.dst_slot[top_idx]
    is_move = sel_kind == KIND_MOVE

    # Non-selected rows are routed out of bounds (JAX scatters drop OOB
    # indices), so duplicate candidate rows can never overwrite an accepted
    # move with a stale no-op value.
    p_pad = jnp.int32(state.num_partitions)
    move_rows = jnp.where(sel & is_move, sel_p, p_pad)
    new_assignment = state.assignment.at[move_rows, sel_slot].set(
        sel_dst_b.astype(state.assignment.dtype), mode="drop")

    lead_rows = jnp.where(sel & ~is_move, sel_p, p_pad)
    new_leader = state.leader_slot.at[lead_rows].set(
        sel_dst_slot.astype(state.leader_slot.dtype), mode="drop")

    new_state = dataclasses.replace(state, assignment=new_assignment,
                                    leader_slot=new_leader)
    return new_state, sel.sum()


def optimize_goal(state: ClusterTensors, goal: Goal,
                  optimized: Sequence[Goal], constraint: BalancingConstraint,
                  cfg: SearchConfig, num_topics: int,
                  masks: ExclusionMasks | None = None,
                  ) -> tuple[ClusterTensors, dict]:
    """Run rounds for one goal until converged (no applicable improving
    action) or the round cap. Host reads one scalar per round.

    Raises OptimizationFailureError if a hard goal still has violations
    after convergence (Goal.java:53-59 semantics).
    """
    masks = masks or ExclusionMasks()
    opt_tuple = tuple(optimized)
    total_applied = 0
    rounds = 0
    for rounds in range(1, cfg.max_rounds + 1):
        state, applied = optimize_round(
            state, goal, opt_tuple, constraint, cfg, num_topics, masks)
        applied = int(applied)
        total_applied += applied
        if applied == 0:
            break

    derived = compute_derived(state, masks.excluded_topics,
                              masks.excluded_replica_move_brokers,
                              masks.excluded_leadership_brokers)
    aux = goal.prepare(state, derived, constraint, num_topics)
    violations = goal.broker_violations(state, derived, constraint, aux)
    objective = float(goal.objective(state, derived, constraint, aux))
    total_violation = float(violations.sum())
    offline_remaining = int(offline_replicas(state).sum())
    succeeded = total_violation <= 1e-6
    if goal.is_hard and not succeeded:
        raise OptimizationFailureError(
            f"hard goal {goal.name} unsatisfied: residual violation "
            f"{total_violation:.4f} after {rounds} rounds")
    info = {
        "goal": goal.name,
        "rounds": rounds,
        "moves_applied": total_applied,
        "residual_violation": total_violation,
        "succeeded": succeeded,
        "objective": objective,
        "offline_remaining": offline_remaining,
    }
    return state, info
