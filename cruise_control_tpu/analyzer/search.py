"""The batched rebalance search.

TPU-native replacement for the reference's greedy inner loop
(AbstractGoal.java:82-135 optimize → rebalanceForBroker → one
maybeApplyBalancingAction at a time). Each round, ONE fused kernel:

1. recomputes derived per-broker state,
2. generates a top-k × top-k grid of candidate actions for the active goal,
3. evaluates the active goal's improvement AND every previously-optimized
   goal's acceptance for all candidates (the lexicographic-constraint stack
   of SURVEY.md §A.3 as boolean masks),
4. picks a conflict-free batch of the best improving candidates
   (scatter-min rank dedup over partition/src/dst), and
5. applies them functionally.

The host loop only reads back one scalar ("moves applied") per round.

The round body is shared with the multi-chip path
(parallel/sharded.py): ``score_round_candidates`` and ``apply_selected``
take a ``psum`` hook / row offset so the same kernels run replicated or
partition-sharded.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp

from ..common.resources import Resource
from ..model.tensors import ClusterTensors, offline_replicas
from .agg import pot_lbi_deltas
from .candidates import (
    KIND_MOVE, attach_cumulative, compute_deltas, generate_candidates,
    select_sources,
)
from .constraint import BalancingConstraint
from .derived import DerivedState, compute_derived
from .goals.base import Goal

_EPS_IMPROVEMENT = 1e-9
_OFFLINE_BONUS = 1e12
# Relative width of the "these scores are effectively tied" window inside
# which the destination-rotation preference may reorder choices.
_TIE_WINDOW = 0.01


class OptimizationFailureError(RuntimeError):
    """A hard goal could not be satisfied
    (OptimizationFailureException equivalent)."""


@dataclasses.dataclass(frozen=True)
class SearchConfig:
    num_sources: int = 64
    num_dests: int = 32
    moves_per_round: int = 32
    max_rounds: int = 200


@partial(jax.tree_util.register_dataclass,
         data_fields=["excluded_topics", "excluded_replica_move_brokers",
                      "excluded_leadership_brokers"],
         meta_fields=[])
@dataclasses.dataclass(frozen=True)
class ExclusionMasks:
    """Traced boolean masks built from OptimizationOptions by the optimizer."""

    excluded_topics: jax.Array | None = None            # [T] bool
    excluded_replica_move_brokers: jax.Array | None = None  # [B] bool
    excluded_leadership_brokers: jax.Array | None = None    # [B] bool


def goal_aux(goal: Goal, state: ClusterTensors, derived: DerivedState,
             constraint: BalancingConstraint, num_topics: int, psum=None):
    """Per-goal aux tensors; the partition-additive partial is psum'd when a
    mesh hook is given (Goal.prepare_partial/finalize_aux contract). The
    agg-carry read path lives in chain._gated_aux — the per-goal kernels
    here stay recompute-only as the equivalence oracle."""
    partial_aux = goal.prepare_partial(state, num_topics)
    if partial_aux is not None and psum is not None:
        partial_aux = jax.tree.map(psum, partial_aux)
    return goal.finalize_aux(partial_aux, state, derived, constraint)


def reduce_per_source(score: jax.Array,
                      layout: tuple[tuple[int, int], ...],
                      row_offset: jax.Array | int = 0,
                      extra_last_col: bool = False) -> jax.Array:
    """Per-source best-destination reduction: each [rows × cols] grid block
    collapses to one candidate per source replica. Without this, equal
    scores cluster one partition's candidates at the head of the global
    sort and the conflict dedup throws most of the round away.

    Tie-breaking: among the columns whose score is within a small relative
    window of the row's best, prefer column ((row + row_offset) mod cols),
    then the next, etc. This spreads near-tied sources across DIFFERENT
    destinations — otherwise all sources chase the single most-attractive
    destination and the one-move-per-destination conflict rule caps the
    round at one move. Columns outside the tie window are never chosen, so
    a genuinely better candidate (e.g. the only one fixing a tiny capacity
    violation) cannot be displaced. ``row_offset`` decorrelates devices in
    the sharded path.

    ``extra_last_col``: the FIRST block's last column is the targeted-
    destination column (generate_candidates ``extra_dst``); it is kept
    OUT of the rotation cycle (rank = cols, i.e. last among ties) so its
    mere presence cannot perturb the rotation arithmetic of the shared
    destinations — an all-invalid targeted column then selects
    bit-identically to no column at all (measured: the modulo shift
    alone flipped the 1k drain-50 fixture 86.0 → 82.74). A targeted
    destination still wins whenever it scores strictly above the tie
    window, which is what it is for."""
    red_parts = []
    offset = 0
    for block_i, (rows, cols) in enumerate(layout):
        block = score[offset:offset + rows * cols].reshape(rows, cols)
        finite = jnp.isfinite(block)
        safe = jnp.where(finite, block, -jnp.inf)
        row_max = safe.max(axis=1, keepdims=True)
        window = _TIE_WINDOW * jnp.maximum(jnp.abs(row_max), 1e-6)
        tied = finite & (safe >= row_max - window)

        rot_cols = cols - 1 if (extra_last_col and block_i == 0) else cols
        col_ids = jnp.arange(cols, dtype=jnp.int32)[None, :]
        row_ids = jnp.arange(rows, dtype=jnp.int32)[:, None] + row_offset
        # Rotation rank: 0 for the row's preferred column, increasing
        # after; the extra column (if any) ranks last among ties.
        rot = jnp.where(col_ids < rot_cols,
                        (col_ids - row_ids) % max(rot_cols, 1), cols)
        best_col = jnp.argmin(jnp.where(tied, rot, cols + 1), axis=1)
        # Rows with no tied (finite) column keep plain argmax (all -inf:
        # conflict selection drops them anyway).
        best_col = jnp.where(tied.any(axis=1), best_col, jnp.argmax(safe, axis=1))
        red_parts.append(offset + jnp.arange(rows) * cols + best_col)
        offset += rows * cols
    return jnp.concatenate(red_parts)


def _conflict_free_top_m(score: jax.Array, partition: jax.Array,
                         src: jax.Array, dst: jax.Array, m: int,
                         num_partitions: int, num_brokers: int,
                         dedupe_brokers: bool | jax.Array = True):
    """Indices of up to ``m`` best-scoring candidates such that no two share
    a partition — nor, when ``dedupe_brokers`` (goals whose scores depend on
    per-broker totals), a source or destination broker. Scatter-min of the
    score-rank per key resolves conflicts in parallel (no sequential scan).
    ``dedupe_brokers`` may be a traced bool (the chain kernel switches it
    per active goal at runtime)."""
    k = min(m, score.shape[0])
    top_score, top_idx = jax.lax.top_k(score, k)
    ok = top_score > _EPS_IMPROVEMENT
    rank = jnp.arange(k, dtype=jnp.int32)

    sel_p = partition[top_idx]
    sel_src = src[top_idx]
    sel_dst = dst[top_idx]

    big = jnp.int32(k + 1)
    rank_eff = jnp.where(ok, rank, big)

    first_p = jnp.full(num_partitions, big, dtype=jnp.int32).at[sel_p].min(rank_eff)
    accept = ok & (first_p[sel_p] == rank)
    if dedupe_brokers is False:
        return top_idx, accept
    first_src = jnp.full(num_brokers, big, dtype=jnp.int32).at[sel_src].min(rank_eff)
    first_dst = jnp.full(num_brokers, big, dtype=jnp.int32).at[sel_dst].min(rank_eff)
    broker_ok = (first_src[sel_src] == rank) & (first_dst[sel_dst] == rank)
    if dedupe_brokers is True:
        accept &= broker_ok
    else:
        accept &= jnp.where(dedupe_brokers, broker_ok, True)
    return top_idx, accept


def cumulative_select(state: ClusterTensors, deltas, score: jax.Array,
                      layout, m: int, moves_cap: int,
                      independent: bool | jax.Array, recheck,
                      extra_last_col: bool = False):
    """Conflict selection with JOINT acceptance instead of broker dedupe.

    The old rule admitted at most ONE move per src/dst broker per round
    (scatter-min dedupe), because each candidate's acceptance was judged
    against round-start aggregates — sound but it serialized per-broker
    throughput (~num_dests accepted moves/round at scale). Here the top-m
    candidates (rank order, one per partition) get pairwise CUMULATIVE
    pre-deltas (attach_cumulative), and ``recheck(sub, has_earlier)``
    re-evaluates every stacked goal's acceptance with those shifts: many
    moves may share a broker as long as their joint effect stays inside
    every goal's bands/limits.

    Returns (top_idx into the full grid, sel mask, selected sub-batch,
    pot_delta, lbi_delta) — the latter three so aggregate-carrying drivers
    can scatter the batch's effect without re-deriving it."""
    red_idx = reduce_per_source(score, layout, extra_last_col=extra_last_col)
    red_score = score[red_idx]
    k = min(m, red_score.shape[0])
    top_score, top_i = jax.lax.top_k(red_score, k)
    idx = red_idx[top_i]
    ok = top_score > _EPS_IMPROVEMENT
    rank = jnp.arange(k, dtype=jnp.int32)
    big = jnp.int32(k + 1)
    rank_eff = jnp.where(ok, rank, big)
    sel_p = deltas.partition[idx]
    first_p = jnp.full(state.num_partitions, big, jnp.int32) \
        .at[sel_p].min(rank_eff)
    part_ok = ok & (first_p[sel_p] == rank)

    sub = jax.tree.map(lambda a: a[idx], deltas)
    pot, lbi = pot_lbi_deltas(state, sub)
    sub, has_earlier = attach_cumulative(sub, part_ok, pot, lbi)
    sel = part_ok & recheck(sub, has_earlier)
    within_cap = jnp.cumsum(sel.astype(jnp.int32)) <= moves_cap
    if independent is True:
        pass
    elif independent is False:
        sel &= within_cap
    else:
        sel &= jnp.where(independent, True, within_cap)
    return idx, sel, sub, pot, lbi


def run_carry_loop(round_body, carry0, max_rounds: int, budget=None):
    """Generic fused-driver scaffold: iterate ``round_body(carry, rounds)
    -> (carry, applied)`` under ``lax.while_loop`` until a round applies
    nothing (or ``max_rounds``) entirely on device — ONE host round-trip
    for the whole loop. ``carry0`` is any pytree (the incremental-aggregate
    drivers carry (state, AggCarry)). Returns (final_carry, total_applied,
    rounds_run).

    ``budget`` (optional TRACED int) further caps the rounds this call may
    run without recompiling per value — the bounded-dispatch driver passes
    the remaining global round budget so a dispatch never overshoots
    ``cfg.max_rounds`` (the static ``max_rounds`` alone would admit up to
    a full dispatch past it).

    This loop IS the megastep (docs/DESIGN.md round 10): the while carry
    ``(carry, total, rounds, last_applied)`` keeps the early-exit flag —
    ``last_applied == 0`` — on device, so a budget-K dispatch that reaches
    its fixed point mid-budget freezes the state and stops WITHOUT a host
    round-trip; the host detects convergence purely from the returned
    ``rounds_run < budget``. That detectability is what the async
    readback pump and its speculative post-convergence dispatch rely on
    (chain.run_bounded_pass)."""
    cap = max_rounds if budget is None else jnp.minimum(
        jnp.int32(max_rounds), budget.astype(jnp.int32))

    def cond(c):
        _carry, _total, rounds, last = c
        return (last > 0) & (rounds < cap)

    def body(c):
        carry, total, rounds, _last = c
        carry, applied = round_body(carry, rounds)
        applied = applied.astype(jnp.int32)
        return carry, total + applied, rounds + 1, applied

    final, total, rounds, _ = jax.lax.while_loop(
        cond, body, (carry0, jnp.int32(0), jnp.int32(0), jnp.int32(1)))
    return final, total, rounds


def run_rounds_loop(round_body, state: ClusterTensors, max_rounds: int,
                    budget=None,
                    ) -> tuple[ClusterTensors, jax.Array, jax.Array]:
    """State-only wrapper of :func:`run_carry_loop` — iterate
    ``round_body(state) -> (new_state, applied)`` to its fixed point.
    Returns (final_state, total_applied, rounds_run). Used by the per-goal
    kernels (the equivalence oracles) and any driver without an aggregate
    carry."""
    return run_carry_loop(lambda s, _r: round_body(s), state, max_rounds,
                          budget=budget)


def score_round_candidates(state: ClusterTensors, masks: ExclusionMasks,
                           goal: Goal, optimized: tuple[Goal, ...],
                           constraint: BalancingConstraint, cfg: SearchConfig,
                           num_topics: int, psum=None, k_src: int | None = None):
    """Shared round body: derived state → candidate grid → lexicographic
    acceptance stack → scored candidates. ``psum`` combines partition-
    additive aggregates across a mesh (None on a single device); ``k_src``
    overrides the per-device source count in the sharded path.

    Returns (cand, deltas, score, layout)."""
    derived = compute_derived(state, masks.excluded_topics,
                              masks.excluded_replica_move_brokers,
                              masks.excluded_leadership_brokers, psum=psum)
    aux = goal_aux(goal, state, derived, constraint, num_topics, psum)
    aux_by_goal = {g.name: goal_aux(g, state, derived, constraint, num_topics, psum)
                   for g in optimized}

    src_score = goal.source_score(state, derived, constraint, aux)
    dst_score = goal.dest_score(state, derived, constraint, aux)
    weight = goal.replica_weight(state, derived, constraint, aux)
    if psum is not None and goal.partition_additive_scores:
        src_score = psum(src_score)

    # Self-healing has priority: replicas stranded on dead brokers are
    # always sources with maximal weight, and moving one scores a large
    # bonus so it wins over pure balance refinements
    # (ClusterModel.selfHealingEligibleReplicas / _fixOfflineReplicasOnly).
    off = offline_replicas(state)  # [P, S]
    b = state.num_brokers
    seg = jnp.where(state.assignment >= 0, state.assignment, b).reshape(-1)
    offline_per_broker = jax.ops.segment_sum(
        off.astype(jnp.float32).reshape(-1), seg, num_segments=b + 1)[:b]
    if psum is not None:
        offline_per_broker = psum(offline_per_broker)
    if not goal.leadership_only:
        src_score = src_score + offline_per_broker
        weight = jnp.where(off, 1e30, weight)  # finite: top-k validity uses isfinite

    # Targeted destination column (Goal.target_dests over the shared
    # source selection, analyzer.fill): SINGLE-DEVICE only (psum None)
    # and scale-gated (targets_enabled). Where enabled, it is appended
    # for every goal — goals without a target rule get an all-invalid
    # column — so the single-device per-goal and chain kernels share one
    # move-block column count; the sharded kernels never append it (and
    # the column stays out of the tie-rotation cycle either way, so the
    # kernels' shared-destination arithmetic agrees).
    from .fill import targets_enabled
    k_eff = k_src or cfg.num_sources
    extra = None
    # psum set = partition-sharded mesh: targeted fills are single-device
    # only (device-local fill ranks collide across shards — see
    # parallel/chain_sharded.py).
    if targets_enabled(state.num_partitions) and not goal.leadership_only \
            and psum is None:
        cand_p, cand_s, src_valid = select_sources(state, src_score, weight,
                                                   k_eff)
        extra = goal.target_dests(state, derived, constraint, aux,
                                  cand_p, cand_s, src_valid)
        if extra is None:
            extra = (jnp.zeros_like(cand_p),
                     jnp.zeros(cand_p.shape, dtype=bool))
        else:
            # Targets pause while any offline replica exists (see
            # chain._chain_round_body).
            extra = (extra[0], extra[1] & ~off.any())

    cand, layout = generate_candidates(state, derived, src_score, dst_score, weight,
                                       k_eff, cfg.num_dests,
                                       goal.include_leadership, goal.leadership_only,
                                       extra_dst=extra)
    deltas = compute_deltas(state, derived, cand)

    accept = deltas.valid
    for g in optimized:
        accept &= g.acceptance(state, derived, constraint,
                               aux_by_goal[g.name], deltas)

    moving_offline = off[deltas.partition, deltas.src_slot] & (deltas.replica_delta > 0)
    imp = goal.improvement(state, derived, constraint, aux, deltas)
    imp = jnp.where(moving_offline & jnp.isfinite(imp) & deltas.valid,
                    jnp.maximum(imp, 0.0) + _OFFLINE_BONUS, imp)
    score = jnp.where(accept, imp, -jnp.inf)
    return cand, deltas, score, layout, (derived, aux, aux_by_goal)


def apply_selected(state: ClusterTensors, sel: jax.Array, sel_p: jax.Array,
                   sel_slot: jax.Array, sel_dst_b: jax.Array,
                   sel_kind: jax.Array, sel_dst_slot: jax.Array,
                   row_offset: jax.Array | int = 0) -> ClusterTensors:
    """Apply a selected move batch functionally. ``sel_p`` holds partition
    row ids relative to ``row_offset`` + local rows (global ids in the
    sharded path); rows outside [0, P_local) and non-selected rows route out
    of bounds — JAX scatters drop OOB indices, so duplicate candidate rows
    can never overwrite an accepted move with a stale no-op value."""
    p_local = state.num_partitions
    local_row = sel_p - row_offset
    in_range = (local_row >= 0) & (local_row < p_local)
    is_move = sel_kind == KIND_MOVE
    p_pad = jnp.int32(p_local)

    move_rows = jnp.where(sel & is_move & in_range, local_row, p_pad)
    new_assignment = state.assignment.at[move_rows, sel_slot].set(
        sel_dst_b.astype(state.assignment.dtype), mode="drop")

    lead_rows = jnp.where(sel & ~is_move & in_range, local_row, p_pad)
    new_leader = state.leader_slot.at[lead_rows].set(
        sel_dst_slot.astype(state.leader_slot.dtype), mode="drop")

    return dataclasses.replace(state, assignment=new_assignment,
                               leader_slot=new_leader)


def _per_broker_top_replicas(state: ClusterTensors, weight: jax.Array,
                             brokers: jax.Array, j: int, largest: bool):
    """For each broker in ``brokers[K]``: the j best replicas it hosts by
    ``weight[P, S]`` (largest or smallest). Returns (flat_idx[K, j],
    valid[K, j]) into the flattened [P*S] replica axis."""
    from ..model.tensors import replica_exists
    exists = replica_exists(state)
    b = state.num_brokers
    seg = jnp.where(state.assignment >= 0, state.assignment, b).reshape(-1)
    flat_w = jnp.where(exists, weight, jnp.nan).reshape(-1)

    def one(broker):
        on_b = (seg == broker) & jnp.isfinite(flat_w)
        key = jnp.where(on_b, flat_w if largest else -flat_w, -jnp.inf)
        vals, idx = jax.lax.top_k(key, j)
        return idx, jnp.isfinite(vals)

    return jax.vmap(one)(brokers)


def swap_grid(state: ClusterTensors, derived: DerivedState,
              src_score: jax.Array, dst_score: jax.Array, weight: jax.Array,
              k_brokers: int = 8, j_replicas: int = 4):
    """The swap candidate grid (AbstractGoal.maybeApplySwapAction:287 + the
    swap search of ResourceDistributionGoal.java:599-687), batched:

    top-k overloaded brokers × top-k donors × (j heaviest source replicas ×
    j lightest destination replicas) → K·K·j·j swap candidates. The source
    replica must outweigh the destination replica (maxSourceReplicaLoad: a
    swap always decreases the overloaded side, :599-687).

    Returns (fwd, rev, net, p1, s1, p2, s2, src_b, dst_b, base_valid) where
    fwd/rev are the directional move legs and net the net transfer."""
    from .candidates import CandidateDeltas

    k = min(k_brokers, state.num_brokers)
    src_vals, src_brokers = jax.lax.top_k(
        jnp.where(src_score > 0, src_score, -jnp.inf), k)
    dst_vals, dst_brokers = jax.lax.top_k(dst_score, k)
    src_b_ok = jnp.isfinite(src_vals)
    dst_b_ok = jnp.isfinite(dst_vals)

    heavy_idx, heavy_ok = _per_broker_top_replicas(
        state, weight, src_brokers, j_replicas, largest=True)    # [K, j]
    light_idx, light_ok = _per_broker_top_replicas(
        state, weight, dst_brokers, j_replicas, largest=False)

    s_dim = state.max_replication_factor
    # Grid: [K_src, K_dst, j, j] flattened.
    n = k * k * j_replicas * j_replicas
    si, di, ai, bi = jnp.meshgrid(jnp.arange(k), jnp.arange(k),
                                  jnp.arange(j_replicas),
                                  jnp.arange(j_replicas), indexing="ij")
    si, di, ai, bi = (x.reshape(-1) for x in (si, di, ai, bi))
    src_b = src_brokers[si]
    dst_b = dst_brokers[di]
    a_flat = heavy_idx[si, ai]
    b_flat = light_idx[di, bi]
    p1, s1 = a_flat // s_dim, a_flat % s_dim
    p2, s2 = b_flat // s_dim, b_flat % s_dim

    base_valid = src_b_ok[si] & dst_b_ok[di] & heavy_ok[si, ai] \
        & light_ok[di, bi] & (src_b != dst_b) \
        & derived.movable_partition[p1] & derived.movable_partition[p2] \
        & derived.allowed_replica_move[dst_b] \
        & derived.allowed_replica_move[src_b]
    # Distinct partitions, cross-hosting checks.
    base_valid &= p1 != p2
    base_valid &= ~(state.assignment[p1] == dst_b[:, None]).any(axis=1)
    base_valid &= ~(state.assignment[p2] == src_b[:, None]).any(axis=1)
    # The swap must shrink the overloaded side.
    w_a = weight[p1, s1]
    w_b = weight[p2, s2]
    base_valid &= w_a > w_b

    # Load vectors travel with the replicas (leadership keeps its replica).
    lead1 = (state.leader_slot[p1] == s1)
    lead2 = (state.leader_slot[p2] == s2)
    # A leader leg may not land on a leadership-excluded broker
    # (GoalUtils.eligibleReplicasForSwap:266 — swap sources are never
    # offline, so no self-healing carve-out is needed here).
    base_valid &= (~lead1) | derived.allowed_leadership[dst_b]
    base_valid &= (~lead2) | derived.allowed_leadership[src_b]
    load_a = jnp.where(lead1[:, None], state.leader_load[p1],
                       state.follower_load[p1])
    load_b = jnp.where(lead2[:, None], state.leader_load[p2],
                       state.follower_load[p2])

    def leg(partition, slot, load_vec, lead, src, dst, valid):
        return CandidateDeltas(
            src_broker=jnp.where(valid, src, 0),
            dst_broker=jnp.where(valid, dst, 0),
            load_delta=jnp.where(valid[:, None], load_vec, 0.0),
            replica_delta=valid.astype(jnp.int32),
            leader_delta=(valid & lead).astype(jnp.int32),
            partition=partition, topic=state.topic[partition],
            src_slot=jnp.where(valid, slot, 0),
            dst_slot=jnp.zeros(n, dtype=jnp.int32), valid=valid)

    fwd = leg(p1, s1, load_a, lead1, src_b, dst_b, base_valid)
    rev = leg(p2, s2, load_b, lead2, dst_b, src_b, base_valid)
    net = CandidateDeltas(
        src_broker=fwd.src_broker, dst_broker=fwd.dst_broker,
        load_delta=jnp.where(base_valid[:, None], load_a - load_b, 0.0),
        replica_delta=jnp.zeros(n, dtype=jnp.int32),
        leader_delta=jnp.where(base_valid,
                               lead1.astype(jnp.int32) - lead2.astype(jnp.int32),
                               0),
        partition=p1, topic=state.topic[p1],
        src_slot=fwd.src_slot, dst_slot=jnp.zeros(n, dtype=jnp.int32),
        valid=base_valid)
    return fwd, rev, net, p1, s1, p2, s2, src_b, dst_b, base_valid


def swap_round_candidates(state: ClusterTensors, masks: ExclusionMasks,
                          goal: Goal, optimized: tuple[Goal, ...],
                          constraint: BalancingConstraint, num_topics: int,
                          k_brokers: int = 8, j_replicas: int = 4):
    """Per-goal swap scoring: the swap grid under the active goal's scores,
    with every previously-optimized goal's swap acceptance (the
    lexicographic stack applied to both legs / the net transfer)."""
    derived = compute_derived(state, masks.excluded_topics,
                              masks.excluded_replica_move_brokers,
                              masks.excluded_leadership_brokers)
    aux = goal_aux(goal, state, derived, constraint, num_topics)
    aux_by_goal = {g.name: goal_aux(g, state, derived, constraint, num_topics)
                   for g in optimized}

    src_score = goal.source_score(state, derived, constraint, aux)
    dst_score = goal.swap_dest_score(state, derived, constraint, aux)
    weight = goal.replica_weight(state, derived, constraint, aux)

    fwd, rev, net, p1, s1, p2, s2, src_b, dst_b, base_valid = swap_grid(
        state, derived, src_score, dst_score, weight, k_brokers, j_replicas)
    accept = base_valid
    for g in optimized:
        accept &= g.swap_acceptance(state, derived, constraint,
                                    aux_by_goal[g.name], fwd, rev, net)
    imp = goal.swap_improvement(state, derived, constraint, aux, fwd, rev,
                                net)
    score = jnp.where(accept, imp, -jnp.inf)
    return score, p1, s1, p2, s2, src_b, dst_b


def apply_swap_selection(state: ClusterTensors, score: jax.Array,
                         p1: jax.Array, s1: jax.Array, p2: jax.Array,
                         s2: jax.Array, src_b: jax.Array, dst_b: jax.Array,
                         moves: int = 8,
                         ) -> tuple[ClusterTensors, jax.Array, jax.Array, jax.Array]:
    """Select + apply a conflict-free batch of scored swaps. Returns
    (new_state, num_applied, top_idx, sel) — the selection indices/mask so
    aggregate-carrying drivers can scatter the swap's effect onto the
    carry.

    Selection: no two accepted swaps may share ANY partition (p1 or p2,
    across roles — else one partition could gain two replicas on a broker
    or a later scatter could half-overwrite an earlier swap) nor ANY
    broker (src or dst, across roles). One scatter array per key space,
    fed from both roles."""
    k = min(moves, score.shape[0])
    top_score, top_idx = jax.lax.top_k(score, k)
    ok = top_score > _EPS_IMPROVEMENT
    rank = jnp.arange(k, dtype=jnp.int32)
    big = jnp.int32(k + 1)
    rank_eff = jnp.where(ok, rank, big)
    sel_p1, sel_p2 = p1[top_idx], p2[top_idx]
    sel_src, sel_dst = src_b[top_idx], dst_b[top_idx]
    first_part = jnp.full(state.num_partitions, big, jnp.int32) \
        .at[sel_p1].min(rank_eff).at[sel_p2].min(rank_eff)
    first_broker = jnp.full(state.num_brokers, big, jnp.int32) \
        .at[sel_src].min(rank_eff).at[sel_dst].min(rank_eff)
    sel = ok & (first_part[sel_p1] == rank) & (first_part[sel_p2] == rank) \
        & (first_broker[sel_src] == rank) & (first_broker[sel_dst] == rank)

    p_pad = jnp.int32(state.num_partitions)
    rows1 = jnp.where(sel, p1[top_idx], p_pad)
    rows2 = jnp.where(sel, p2[top_idx], p_pad)
    new_assignment = state.assignment \
        .at[rows1, s1[top_idx]].set(dst_b[top_idx].astype(state.assignment.dtype),
                                    mode="drop") \
        .at[rows2, s2[top_idx]].set(src_b[top_idx].astype(state.assignment.dtype),
                                    mode="drop")
    return (dataclasses.replace(state, assignment=new_assignment), sel.sum(),
            top_idx, sel)


def _swap_round_body(state: ClusterTensors, goal: Goal,
                     optimized: tuple[Goal, ...],
                     constraint: BalancingConstraint, num_topics: int,
                     masks: ExclusionMasks, moves: int = 8,
                     ) -> tuple[ClusterTensors, jax.Array]:
    """One batched swap round (traced body)."""
    score, p1, s1, p2, s2, src_b, dst_b = swap_round_candidates(
        state, masks, goal, optimized, constraint, num_topics)
    new_state, applied, _idx, _sel = apply_swap_selection(
        state, score, p1, s1, p2, s2, src_b, dst_b, moves)
    return new_state, applied


@partial(jax.jit, static_argnames=("goal", "optimized", "constraint",
                                   "num_topics", "moves"))
def swap_round(state: ClusterTensors, goal: Goal, optimized: tuple[Goal, ...],
               constraint: BalancingConstraint, num_topics: int,
               masks: ExclusionMasks, moves: int = 8,
               ) -> tuple[ClusterTensors, jax.Array]:
    """One batched swap round. Returns (new_state, num_swaps_applied)."""
    return _swap_round_body(state, goal, optimized, constraint, num_topics,
                            masks, moves)


def _round_body(state: ClusterTensors, goal: Goal, optimized: tuple[Goal, ...],
                constraint: BalancingConstraint, cfg: SearchConfig,
                num_topics: int, masks: ExclusionMasks,
                ) -> tuple[ClusterTensors, jax.Array]:
    """One search round (traced body shared by optimize_round and the fused
    on-device driver)."""
    cand, deltas, score, layout, (derived, aux, aux_by) = \
        score_round_candidates(state, masks, goal, optimized, constraint,
                               cfg, num_topics)

    independent = goal.independent_per_broker and not optimized
    m = max(cfg.moves_per_round, cfg.num_sources)

    def recheck(sub, has_earlier):
        """Joint acceptance of the selected batch: every stacked goal with
        cumulative pre-deltas, plus the ACTIVE goal's own acceptance for
        candidates that interact with an earlier one (guards against
        jointly overshooting its own band; the first candidate per broker
        keeps single-candidate semantics)."""
        a = jnp.ones(sub.valid.shape[0], dtype=bool)
        for g in optimized:
            a &= g.acceptance(state, derived, constraint, aux_by[g.name], sub)
        a &= (~has_earlier) | goal.acceptance(state, derived, constraint,
                                              aux, sub)
        return a

    from .fill import targets_enabled
    top_idx, sel, _sub, _pot, _lbi = cumulative_select(
        state, deltas, score, layout, m, cfg.moves_per_round, independent,
        recheck,
        extra_last_col=targets_enabled(state.num_partitions)
        and not goal.leadership_only)
    new_state = apply_selected(
        state, sel, deltas.partition[top_idx], deltas.src_slot[top_idx],
        deltas.dst_broker[top_idx], cand.kind[top_idx], cand.dst_slot[top_idx])
    return new_state, sel.sum()


@partial(jax.jit, static_argnames=("goal", "optimized", "constraint", "cfg",
                                   "num_topics"))
def optimize_round(state: ClusterTensors, goal: Goal,
                   optimized: tuple[Goal, ...], constraint: BalancingConstraint,
                   cfg: SearchConfig, num_topics: int,
                   masks: ExclusionMasks) -> tuple[ClusterTensors, jax.Array]:
    """One fused search round for ``goal``. Returns (new_state, num_applied)."""
    return _round_body(state, goal, optimized, constraint, cfg, num_topics,
                       masks)


@partial(jax.jit, static_argnames=("goal", "optimized", "constraint", "cfg",
                                   "num_topics"))
def optimize_rounds(state: ClusterTensors, goal: Goal,
                    optimized: tuple[Goal, ...],
                    constraint: BalancingConstraint, cfg: SearchConfig,
                    num_topics: int, masks: ExclusionMasks,
                    ) -> tuple[ClusterTensors, jax.Array, jax.Array]:
    """The FUSED multi-round driver: `lax.while_loop` runs search rounds
    until convergence (or cfg.max_rounds) entirely on device — ONE host
    round-trip per goal instead of one per round. This is what makes the
    solver viable over a high-latency device link (and faster everywhere:
    no per-round dispatch).

    Returns (final_state, total_moves, rounds_run)."""
    return run_rounds_loop(
        lambda s: _round_body(s, goal, optimized, constraint, cfg,
                              num_topics, masks),
        state, cfg.max_rounds)


@partial(jax.jit, static_argnames=("goal", "optimized", "constraint",
                                   "num_topics", "moves", "max_rounds"))
def swap_rounds(state: ClusterTensors, goal: Goal, optimized: tuple[Goal, ...],
                constraint: BalancingConstraint, num_topics: int,
                masks: ExclusionMasks, moves: int = 8, max_rounds: int = 64,
                ) -> tuple[ClusterTensors, jax.Array, jax.Array]:
    """Fused swap-phase driver (while_loop analogue of optimize_rounds)."""
    return run_rounds_loop(
        lambda s: _swap_round_body(s, goal, optimized, constraint,
                                   num_topics, masks, moves),
        state, max_rounds)


def optimize_goal(state: ClusterTensors, goal: Goal,
                  optimized: Sequence[Goal], constraint: BalancingConstraint,
                  cfg: SearchConfig, num_topics: int,
                  masks: ExclusionMasks | None = None,
                  ) -> tuple[ClusterTensors, dict]:
    """Run rounds for one goal until converged (no applicable improving
    action) or the round cap. Host reads one scalar per round.

    Raises OptimizationFailureError if a hard goal still has violations
    after convergence (Goal.java:53-59 semantics).
    """
    masks = masks or ExclusionMasks()
    opt_tuple = tuple(optimized)
    total_applied = 0
    total_swaps = 0
    rounds = 0
    # Fused drivers: ONE device call runs the whole move loop to
    # convergence; swap phases interleave only for swap-capable goals
    # (ResourceDistributionGoal.java:421-430: swaps after moves stall).
    while rounds < cfg.max_rounds:
        state, moves, r = optimize_rounds(
            state, goal, opt_tuple, constraint, cfg, num_topics, masks)
        total_applied += int(moves)
        rounds += int(r)
        if not goal.supports_swap:
            break
        state, swapped, sr = swap_rounds(
            state, goal, opt_tuple, constraint, num_topics, masks)
        swapped = int(swapped)
        total_swaps += swapped
        total_applied += swapped
        rounds += int(sr)
        if swapped == 0:
            break

    derived = compute_derived(state, masks.excluded_topics,
                              masks.excluded_replica_move_brokers,
                              masks.excluded_leadership_brokers)
    aux = goal.prepare(state, derived, constraint, num_topics)
    violations = goal.broker_violations(state, derived, constraint, aux)
    objective = float(goal.objective(state, derived, constraint, aux))
    total_violation = float(violations.sum())
    offline_remaining = int(offline_replicas(state).sum())
    succeeded = total_violation <= 1e-6
    if goal.is_hard and not succeeded:
        raise OptimizationFailureError(
            f"hard goal {goal.name} unsatisfied: residual violation "
            f"{total_violation:.4f} after {rounds} rounds")
    info = {
        "goal": goal.name,
        "rounds": rounds,
        "moves_applied": total_applied,
        "swaps_applied": total_swaps,
        "residual_violation": total_violation,
        "succeeded": succeeded,
        "objective": objective,
        "offline_remaining": offline_remaining,
    }
    return state, info
