"""The batched rebalance search.

TPU-native replacement for the reference's greedy inner loop
(AbstractGoal.java:82-135 optimize → rebalanceForBroker → one
maybeApplyBalancingAction at a time). Each round, ONE fused kernel:

1. recomputes derived per-broker state,
2. generates a top-k × top-k grid of candidate actions for the active goal,
3. evaluates the active goal's improvement AND every previously-optimized
   goal's acceptance for all candidates (the lexicographic-constraint stack
   of SURVEY.md §A.3 as boolean masks),
4. picks a conflict-free batch of the best improving candidates
   (scatter-min rank dedup over partition/src/dst), and
5. applies them functionally.

The host loop only reads back one scalar ("moves applied") per round.

The round body is shared with the multi-chip path
(parallel/sharded.py): ``score_round_candidates`` and ``apply_selected``
take a ``psum`` hook / row offset so the same kernels run replicated or
partition-sharded.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp

from ..model.tensors import ClusterTensors, offline_replicas
from .candidates import KIND_MOVE, compute_deltas, generate_candidates
from .constraint import BalancingConstraint
from .derived import DerivedState, compute_derived
from .goals.base import Goal

_EPS_IMPROVEMENT = 1e-9
_OFFLINE_BONUS = 1e12
# Relative width of the "these scores are effectively tied" window inside
# which the destination-rotation preference may reorder choices.
_TIE_WINDOW = 0.01


class OptimizationFailureError(RuntimeError):
    """A hard goal could not be satisfied
    (OptimizationFailureException equivalent)."""


@dataclasses.dataclass(frozen=True)
class SearchConfig:
    num_sources: int = 64
    num_dests: int = 32
    moves_per_round: int = 32
    max_rounds: int = 200


@partial(jax.tree_util.register_dataclass,
         data_fields=["excluded_topics", "excluded_replica_move_brokers",
                      "excluded_leadership_brokers"],
         meta_fields=[])
@dataclasses.dataclass(frozen=True)
class ExclusionMasks:
    """Traced boolean masks built from OptimizationOptions by the optimizer."""

    excluded_topics: jax.Array | None = None            # [T] bool
    excluded_replica_move_brokers: jax.Array | None = None  # [B] bool
    excluded_leadership_brokers: jax.Array | None = None    # [B] bool


def goal_aux(goal: Goal, state: ClusterTensors, derived: DerivedState,
             constraint: BalancingConstraint, num_topics: int, psum=None):
    """Per-goal aux tensors; the partition-additive partial is psum'd when a
    mesh hook is given (Goal.prepare_partial/finalize_aux contract)."""
    partial_aux = goal.prepare_partial(state, num_topics)
    if partial_aux is not None and psum is not None:
        partial_aux = jax.tree.map(psum, partial_aux)
    return goal.finalize_aux(partial_aux, state, derived, constraint)


def reduce_per_source(score: jax.Array,
                      layout: tuple[tuple[int, int], ...],
                      row_offset: jax.Array | int = 0) -> jax.Array:
    """Per-source best-destination reduction: each [rows × cols] grid block
    collapses to one candidate per source replica. Without this, equal
    scores cluster one partition's candidates at the head of the global
    sort and the conflict dedup throws most of the round away.

    Tie-breaking: among the columns whose score is within a small relative
    window of the row's best, prefer column ((row + row_offset) mod cols),
    then the next, etc. This spreads near-tied sources across DIFFERENT
    destinations — otherwise all sources chase the single most-attractive
    destination and the one-move-per-destination conflict rule caps the
    round at one move. Columns outside the tie window are never chosen, so
    a genuinely better candidate (e.g. the only one fixing a tiny capacity
    violation) cannot be displaced. ``row_offset`` decorrelates devices in
    the sharded path."""
    red_parts = []
    offset = 0
    for rows, cols in layout:
        block = score[offset:offset + rows * cols].reshape(rows, cols)
        finite = jnp.isfinite(block)
        safe = jnp.where(finite, block, -jnp.inf)
        row_max = safe.max(axis=1, keepdims=True)
        window = _TIE_WINDOW * jnp.maximum(jnp.abs(row_max), 1e-6)
        tied = finite & (safe >= row_max - window)

        col_ids = jnp.arange(cols, dtype=jnp.int32)[None, :]
        row_ids = jnp.arange(rows, dtype=jnp.int32)[:, None] + row_offset
        # Rotation rank: 0 for the row's preferred column, increasing after.
        rot = (col_ids - row_ids) % cols
        best_col = jnp.argmin(jnp.where(tied, rot, cols + 1), axis=1)
        # Rows with no tied (finite) column keep plain argmax (all -inf:
        # conflict selection drops them anyway).
        best_col = jnp.where(tied.any(axis=1), best_col, jnp.argmax(safe, axis=1))
        red_parts.append(offset + jnp.arange(rows) * cols + best_col)
        offset += rows * cols
    return jnp.concatenate(red_parts)


def _conflict_free_top_m(score: jax.Array, partition: jax.Array,
                         src: jax.Array, dst: jax.Array, m: int,
                         num_partitions: int, num_brokers: int):
    """Indices of up to ``m`` best-scoring candidates such that no two share
    a partition, source broker, or destination broker. Scatter-min of the
    score-rank per key resolves conflicts in parallel (no sequential scan)."""
    k = min(m, score.shape[0])
    top_score, top_idx = jax.lax.top_k(score, k)
    ok = top_score > _EPS_IMPROVEMENT
    rank = jnp.arange(k, dtype=jnp.int32)

    sel_p = partition[top_idx]
    sel_src = src[top_idx]
    sel_dst = dst[top_idx]

    big = jnp.int32(k + 1)
    rank_eff = jnp.where(ok, rank, big)

    first_p = jnp.full(num_partitions, big, dtype=jnp.int32).at[sel_p].min(rank_eff)
    first_src = jnp.full(num_brokers, big, dtype=jnp.int32).at[sel_src].min(rank_eff)
    first_dst = jnp.full(num_brokers, big, dtype=jnp.int32).at[sel_dst].min(rank_eff)

    accept = ok & (first_p[sel_p] == rank) & (first_src[sel_src] == rank) \
        & (first_dst[sel_dst] == rank)
    return top_idx, accept


def score_round_candidates(state: ClusterTensors, masks: ExclusionMasks,
                           goal: Goal, optimized: tuple[Goal, ...],
                           constraint: BalancingConstraint, cfg: SearchConfig,
                           num_topics: int, psum=None, k_src: int | None = None):
    """Shared round body: derived state → candidate grid → lexicographic
    acceptance stack → scored candidates. ``psum`` combines partition-
    additive aggregates across a mesh (None on a single device); ``k_src``
    overrides the per-device source count in the sharded path.

    Returns (cand, deltas, score, layout)."""
    derived = compute_derived(state, masks.excluded_topics,
                              masks.excluded_replica_move_brokers,
                              masks.excluded_leadership_brokers, psum=psum)
    aux = goal_aux(goal, state, derived, constraint, num_topics, psum)
    aux_by_goal = {g.name: goal_aux(g, state, derived, constraint, num_topics, psum)
                   for g in optimized}

    src_score = goal.source_score(state, derived, constraint, aux)
    dst_score = goal.dest_score(state, derived, constraint, aux)
    weight = goal.replica_weight(state, derived, constraint, aux)
    if psum is not None and goal.partition_additive_scores:
        src_score = psum(src_score)

    # Self-healing has priority: replicas stranded on dead brokers are
    # always sources with maximal weight, and moving one scores a large
    # bonus so it wins over pure balance refinements
    # (ClusterModel.selfHealingEligibleReplicas / _fixOfflineReplicasOnly).
    off = offline_replicas(state)  # [P, S]
    b = state.num_brokers
    seg = jnp.where(state.assignment >= 0, state.assignment, b).reshape(-1)
    offline_per_broker = jax.ops.segment_sum(
        off.astype(jnp.float32).reshape(-1), seg, num_segments=b + 1)[:b]
    if psum is not None:
        offline_per_broker = psum(offline_per_broker)
    if not goal.leadership_only:
        src_score = src_score + offline_per_broker
        weight = jnp.where(off, 1e30, weight)  # finite: top-k validity uses isfinite

    cand, layout = generate_candidates(state, derived, src_score, dst_score, weight,
                                       k_src or cfg.num_sources, cfg.num_dests,
                                       goal.include_leadership, goal.leadership_only)
    deltas = compute_deltas(state, derived, cand)

    accept = deltas.valid
    for g in optimized:
        accept &= g.acceptance(state, derived, constraint,
                               aux_by_goal[g.name], deltas)

    moving_offline = off[deltas.partition, deltas.src_slot] & (deltas.replica_delta > 0)
    imp = goal.improvement(state, derived, constraint, aux, deltas)
    imp = jnp.where(moving_offline & jnp.isfinite(imp) & deltas.valid,
                    jnp.maximum(imp, 0.0) + _OFFLINE_BONUS, imp)
    score = jnp.where(accept, imp, -jnp.inf)
    return cand, deltas, score, layout


def apply_selected(state: ClusterTensors, sel: jax.Array, sel_p: jax.Array,
                   sel_slot: jax.Array, sel_dst_b: jax.Array,
                   sel_kind: jax.Array, sel_dst_slot: jax.Array,
                   row_offset: jax.Array | int = 0) -> ClusterTensors:
    """Apply a selected move batch functionally. ``sel_p`` holds partition
    row ids relative to ``row_offset`` + local rows (global ids in the
    sharded path); rows outside [0, P_local) and non-selected rows route out
    of bounds — JAX scatters drop OOB indices, so duplicate candidate rows
    can never overwrite an accepted move with a stale no-op value."""
    p_local = state.num_partitions
    local_row = sel_p - row_offset
    in_range = (local_row >= 0) & (local_row < p_local)
    is_move = sel_kind == KIND_MOVE
    p_pad = jnp.int32(p_local)

    move_rows = jnp.where(sel & is_move & in_range, local_row, p_pad)
    new_assignment = state.assignment.at[move_rows, sel_slot].set(
        sel_dst_b.astype(state.assignment.dtype), mode="drop")

    lead_rows = jnp.where(sel & ~is_move & in_range, local_row, p_pad)
    new_leader = state.leader_slot.at[lead_rows].set(
        sel_dst_slot.astype(state.leader_slot.dtype), mode="drop")

    return dataclasses.replace(state, assignment=new_assignment,
                               leader_slot=new_leader)


@partial(jax.jit, static_argnames=("goal", "optimized", "constraint", "cfg",
                                   "num_topics"))
def optimize_round(state: ClusterTensors, goal: Goal,
                   optimized: tuple[Goal, ...], constraint: BalancingConstraint,
                   cfg: SearchConfig, num_topics: int,
                   masks: ExclusionMasks) -> tuple[ClusterTensors, jax.Array]:
    """One fused search round for ``goal``. Returns (new_state, num_applied)."""
    cand, deltas, score, layout = score_round_candidates(
        state, masks, goal, optimized, constraint, cfg, num_topics)

    red_idx = reduce_per_source(score, layout)

    top_idx_red, sel = _conflict_free_top_m(
        score[red_idx], deltas.partition[red_idx], deltas.src_broker[red_idx],
        deltas.dst_broker[red_idx], cfg.moves_per_round, state.num_partitions,
        state.num_brokers)
    top_idx = red_idx[top_idx_red]

    new_state = apply_selected(
        state, sel, deltas.partition[top_idx], deltas.src_slot[top_idx],
        deltas.dst_broker[top_idx], cand.kind[top_idx], cand.dst_slot[top_idx])
    return new_state, sel.sum()


def optimize_goal(state: ClusterTensors, goal: Goal,
                  optimized: Sequence[Goal], constraint: BalancingConstraint,
                  cfg: SearchConfig, num_topics: int,
                  masks: ExclusionMasks | None = None,
                  ) -> tuple[ClusterTensors, dict]:
    """Run rounds for one goal until converged (no applicable improving
    action) or the round cap. Host reads one scalar per round.

    Raises OptimizationFailureError if a hard goal still has violations
    after convergence (Goal.java:53-59 semantics).
    """
    masks = masks or ExclusionMasks()
    opt_tuple = tuple(optimized)
    total_applied = 0
    rounds = 0
    for rounds in range(1, cfg.max_rounds + 1):
        state, applied = optimize_round(
            state, goal, opt_tuple, constraint, cfg, num_topics, masks)
        applied = int(applied)
        total_applied += applied
        if applied == 0:
            break

    derived = compute_derived(state, masks.excluded_topics,
                              masks.excluded_replica_move_brokers,
                              masks.excluded_leadership_brokers)
    aux = goal.prepare(state, derived, constraint, num_topics)
    violations = goal.broker_violations(state, derived, constraint, aux)
    objective = float(goal.objective(state, derived, constraint, aux))
    total_violation = float(violations.sum())
    offline_remaining = int(offline_replicas(state).sum())
    succeeded = total_violation <= 1e-6
    if goal.is_hard and not succeeded:
        raise OptimizationFailureError(
            f"hard goal {goal.name} unsatisfied: residual violation "
            f"{total_violation:.4f} after {rounds} rounds")
    info = {
        "goal": goal.name,
        "rounds": rounds,
        "moves_applied": total_applied,
        "residual_violation": total_violation,
        "succeeded": succeeded,
        "objective": objective,
        "offline_remaining": offline_remaining,
    }
    return state, info
