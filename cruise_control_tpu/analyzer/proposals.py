"""Proposal extraction: diff of assignment arrays.

Reference parity: AnalyzerUtils.getDiff:47-130 + ExecutionProposal.java —
proposals are NOT accumulated during search; they are the diff between the
initial and final (replica list, leader) state, so transient intra-search
shuffles cost nothing (SURVEY.md §A.5). The tensor model gets this for free
by comparing assignment/leader arrays.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..model.tensors import ClusterMeta, ClusterTensors


@dataclasses.dataclass(frozen=True)
class ExecutionProposal:
    """One partition's reassignment (ExecutionProposal.java:309LoC):
    broker ids (not indices), new replica order leader-first.

    A proposal may additionally (or only) carry an intra-broker JBOD leg:
    the replica on ``logdir_broker`` moves ``source_logdir`` →
    ``destination_logdir`` (ReplicaPlacementInfo logdir semantics; executed
    via alterReplicaLogDirs, Executor.java:1672)."""

    topic: str
    partition: int
    old_leader: int
    old_replicas: tuple[int, ...]
    new_replicas: tuple[int, ...]
    new_leader: int
    logdir_broker: int = -1
    source_logdir: str | None = None
    destination_logdir: str | None = None
    # Partition size (ExecutionProposal.dataToMoveInMB): what each new
    # replica must copy; feeds throttling decisions and the executor's
    # movement-rate alerting.
    data_to_move_mb: float = 0.0

    @property
    def is_leadership_only(self) -> bool:
        return set(self.old_replicas) == set(self.new_replicas) \
            and self.old_leader != self.new_leader

    @property
    def has_logdir_move(self) -> bool:
        return self.logdir_broker >= 0 and self.destination_logdir is not None

    @property
    def replicas_to_add(self) -> tuple[int, ...]:
        return tuple(sorted(set(self.new_replicas) - set(self.old_replicas)))

    @property
    def replicas_to_remove(self) -> tuple[int, ...]:
        return tuple(sorted(set(self.old_replicas) - set(self.new_replicas)))


def _ordered_replicas(assignment_row: np.ndarray, leader_slot: int,
                      broker_ids: list[int]) -> tuple[tuple[int, ...], int]:
    """Replica broker ids with the leader first (ExecutionProposal
    convention), -1-padded slots dropped."""
    slots = [s for s, b in enumerate(assignment_row) if b >= 0]
    if not slots:
        return (), -1
    leader_b = int(assignment_row[leader_slot]) if 0 <= leader_slot < len(assignment_row) \
        and assignment_row[leader_slot] >= 0 else -1
    ordered = []
    if leader_b >= 0:
        ordered.append(leader_b)
    for s in slots:
        b = int(assignment_row[s])
        if b != leader_b:
            ordered.append(b)
    ids = tuple(broker_ids[b] for b in ordered)
    leader_id = broker_ids[leader_b] if leader_b >= 0 else -1
    return ids, leader_id


def diff_proposals(initial: ClusterTensors, final: ClusterTensors,
                   meta: ClusterMeta) -> list[ExecutionProposal]:
    """Set of ExecutionProposals for partitions whose replica set, order, or
    leader changed (AnalyzerUtils.getDiff)."""
    from ..common.resources import Resource

    a0 = np.asarray(initial.assignment)
    a1 = np.asarray(final.assignment)
    l0 = np.asarray(initial.leader_slot)
    l1 = np.asarray(final.leader_slot)
    mask = np.asarray(initial.partition_mask)
    disk_mb = np.asarray(initial.leader_load[:, int(Resource.DISK)])

    changed = ((a0 != a1).any(axis=1) | (l0 != l1)) & mask
    proposals: list[ExecutionProposal] = []
    for p in np.nonzero(changed)[0]:
        old_reps, old_leader = _ordered_replicas(a0[p], int(l0[p]), meta.broker_ids)
        new_reps, new_leader = _ordered_replicas(a1[p], int(l1[p]), meta.broker_ids)
        if old_reps == new_reps and old_leader == new_leader:
            continue
        topic, pnum = meta.partition_index[p]
        proposals.append(ExecutionProposal(
            topic=topic, partition=pnum, old_leader=old_leader,
            old_replicas=old_reps, new_replicas=new_reps,
            new_leader=new_leader, data_to_move_mb=float(disk_mb[p])))
    return proposals
