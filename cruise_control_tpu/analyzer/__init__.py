from .constraint import BalancingConstraint, OptimizationOptions, BALANCE_MARGIN
from .derived import DerivedState, compute_derived, count_limits, resource_limits
from .candidates import Candidates, CandidateDeltas, compute_deltas, generate_candidates
from .proposals import ExecutionProposal, diff_proposals
from .search import (ExclusionMasks, OptimizationFailureError, SearchConfig,
                     optimize_goal, optimize_round)
from .optimizer import (GoalOptimizer, GoalResult, OptimizerResult,
                        balancedness_score, goals_by_priority)
