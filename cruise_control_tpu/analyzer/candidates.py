"""Candidate actions: batched generation and delta evaluation.

The TPU-native replacement for the reference's per-replica greedy inner loop
(AbstractGoal.rebalanceForBroker → maybeApplyBalancingAction): instead of
trying one action at a time, the solver materializes a fixed-size batch of
candidate actions each round, evaluates every goal's acceptance and the
active goal's improvement for ALL of them in one fused kernel, and applies a
conflict-free subset.

A candidate is (kind, partition, src_slot, dst_broker, dst_slot):
- kind 0 = INTER_BROKER_REPLICA_MOVEMENT: replica at (partition, src_slot)
  moves to dst_broker (keeps leadership if it was the leader).
- kind 1 = LEADERSHIP_MOVEMENT: leadership transfers from the current leader
  slot to dst_slot (dst_broker is derived = broker of dst_slot).
"""

from __future__ import annotations

import dataclasses
import os
from functools import partial

import jax
import jax.numpy as jnp

from ..model.tensors import ClusterTensors, is_leader_slot, replica_exists
from .derived import DerivedState

KIND_MOVE = 0
KIND_LEADERSHIP = 1


@partial(jax.tree_util.register_dataclass,
         data_fields=["kind", "partition", "src_slot", "dst_broker", "dst_slot", "valid"],
         meta_fields=[])
@dataclasses.dataclass(frozen=True)
class Candidates:
    kind: jax.Array        # [N] int8
    partition: jax.Array   # [N] int32
    src_slot: jax.Array    # [N] int32
    dst_broker: jax.Array  # [N] int32
    dst_slot: jax.Array    # [N] int32 (leadership only)
    valid: jax.Array       # [N] bool

    @property
    def n(self) -> int:
        return self.kind.shape[0]


@partial(jax.tree_util.register_dataclass,
         data_fields=["src_broker", "dst_broker", "load_delta", "replica_delta",
                      "leader_delta", "partition", "topic", "src_slot",
                      "dst_slot", "valid", "pre_src_load", "pre_dst_load",
                      "pre_src_count", "pre_dst_count", "pre_src_leaders",
                      "pre_dst_leaders", "pre_src_topic_count",
                      "pre_dst_topic_count", "pre_src_topic_leaders",
                      "pre_dst_pot", "pre_dst_lbi"],
         meta_fields=[])
@dataclasses.dataclass(frozen=True)
class CandidateDeltas:
    """Per-candidate effect: src loses, dst gains.

    The optional ``pre_*`` fields carry the CUMULATIVE effect of
    higher-ranked candidates selected in the same round on this candidate's
    src/dst brokers (attach_cumulative). Goal acceptance adds them to the
    round-start aggregates so a batch of same-broker moves is judged
    jointly — the sound relaxation of one-move-per-broker-per-round.
    Directionally conservative: dst pre terms count only inflows, src pre
    terms only outflows, so a rejected earlier candidate can only make the
    check stricter, never looser. ``None`` = single-candidate semantics."""

    src_broker: jax.Array    # [N] int32
    dst_broker: jax.Array    # [N] int32
    load_delta: jax.Array    # [N, R] — leaves src, arrives dst
    replica_delta: jax.Array  # [N] int32 (1 for moves, 0 for leadership)
    leader_delta: jax.Array   # [N] int32 (1 if leadership follows the action)
    partition: jax.Array     # [N] int32
    topic: jax.Array         # [N] int32
    src_slot: jax.Array      # [N] int32
    dst_slot: jax.Array      # [N] int32 (leadership target slot; 0 for moves)
    valid: jax.Array         # [N] bool
    pre_src_load: jax.Array | None = None        # [N, R]
    pre_dst_load: jax.Array | None = None        # [N, R]
    pre_src_count: jax.Array | None = None       # [N] f32
    pre_dst_count: jax.Array | None = None       # [N] f32
    pre_src_leaders: jax.Array | None = None     # [N] f32
    pre_dst_leaders: jax.Array | None = None     # [N] f32
    pre_src_topic_count: jax.Array | None = None   # [N] f32 (same topic)
    pre_dst_topic_count: jax.Array | None = None   # [N] f32
    pre_src_topic_leaders: jax.Array | None = None  # [N] f32
    pre_dst_pot: jax.Array | None = None         # [N] f32 potential NW-out
    pre_dst_lbi: jax.Array | None = None         # [N] f32 leader bytes-in

    def pre0(self, name: str):
        """Pre-term or 0.0 (single-candidate semantics when absent)."""
        value = getattr(self, name)
        return 0.0 if value is None else value

    def pre_load(self, name: str, r: int):
        value = getattr(self, name)
        return 0.0 if value is None else value[:, r]


def compute_deltas(state: ClusterTensors, derived: DerivedState,
                   cand: Candidates) -> CandidateDeltas:
    """Gather the (src, dst, Δload) tuple for every candidate; also folds the
    structural legitimacy checks (GoalUtils.legitMove: destination must not
    already host the partition, source must exist, destination must be an
    alive allowed broker, leadership destination must be a live replica)."""
    p = cand.partition
    b = state.num_brokers
    assign_p = state.assignment[p]              # [N, S]
    leader_slot_p = state.leader_slot[p]        # [N]

    is_move = cand.kind == KIND_MOVE
    # src broker: replica's broker for moves; current leader's broker for leadership.
    src_slot = jnp.where(is_move, cand.src_slot, leader_slot_p)
    src_broker = jnp.take_along_axis(
        assign_p, jnp.maximum(src_slot, 0)[:, None], axis=1)[:, 0]
    dst_broker = jnp.where(
        is_move, cand.dst_broker,
        jnp.take_along_axis(assign_p, jnp.maximum(cand.dst_slot, 0)[:, None], axis=1)[:, 0])

    moving_is_leader = src_slot == leader_slot_p
    lead = state.leader_load[p]      # [N, R]
    foll = state.follower_load[p]    # [N, R]
    move_vec = jnp.where(moving_is_leader[:, None], lead, foll)
    leadership_vec = lead - foll
    load_delta = jnp.where(is_move[:, None], move_vec, leadership_vec)

    replica_delta = is_move.astype(jnp.int32)
    leader_delta = (jnp.where(is_move, moving_is_leader, True)).astype(jnp.int32)

    # Structural legitimacy -------------------------------------------------
    src_exists = (src_slot >= 0) & (jnp.take_along_axis(
        assign_p, jnp.maximum(src_slot, 0)[:, None], axis=1)[:, 0] >= 0)
    dst_in_range = (dst_broker >= 0) & (dst_broker < b)
    dst_safe = jnp.clip(dst_broker, 0, b - 1)
    dst_alive = derived.alive[dst_safe] & dst_in_range

    # Destination must not already host the partition (moves only);
    # comparing against all S slots of the partition.
    already_hosts = (assign_p == dst_broker[:, None]).any(axis=1)
    # Moving a LEADER replica transfers leadership with it, so destinations
    # excluded for leadership are ineligible for leader-replica moves
    # (GoalUtils.filterOutBrokersExcludedForLeadership:120-137: excluded
    # brokers are removed when action is LEADERSHIP_MOVEMENT or
    # replica.isLeader()). Offline replicas are exempt — self-healing
    # placement must proceed even onto leadership-excluded brokers
    # (eligibleReplicasForSwap's !isOriginalOffline carve-out).
    src_safe = jnp.clip(src_broker, 0, b - 1)
    src_offline = ~derived.alive[src_safe]
    lead_dst_ok = (~moving_is_leader) | src_offline \
        | derived.allowed_leadership[dst_safe]
    move_ok = (~already_hosts) & derived.allowed_replica_move[dst_safe] \
        & (src_broker != dst_broker) & lead_dst_ok
    # Leadership: destination slot must hold a live replica on an
    # allowed-for-leadership broker, and differ from the current leader.
    dst_slot_live = jnp.take_along_axis(
        assign_p, jnp.maximum(cand.dst_slot, 0)[:, None], axis=1)[:, 0] >= 0
    lead_ok = dst_slot_live & (cand.dst_slot != leader_slot_p) & (cand.dst_slot >= 0) \
        & derived.allowed_leadership[dst_safe] & (leader_slot_p >= 0)

    valid = cand.valid & derived.movable_partition[p] & src_exists & dst_alive \
        & jnp.where(is_move, move_ok, lead_ok)

    return CandidateDeltas(
        src_broker=jnp.where(valid, src_broker, 0),
        dst_broker=jnp.where(valid, dst_safe, 0),
        load_delta=jnp.where(valid[:, None], load_delta, 0.0),
        replica_delta=jnp.where(valid, replica_delta, 0),
        leader_delta=jnp.where(valid, leader_delta, 0),
        partition=p,
        topic=state.topic[p],
        src_slot=jnp.where(valid, src_slot, 0),
        dst_slot=jnp.where(valid & ~is_move, cand.dst_slot, 0),
        valid=valid,
    )


def select_sources(state: ClusterTensors, source_score: jax.Array,
                   replica_weight: jax.Array, num_sources: int,
                   ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """The move grid's source-replica selection (broker-diverse top-k; see
    generate_candidates). Returns (cand_p [k], cand_s [k], src_valid [k]).

    Deterministic in its inputs and called by both generate_candidates and
    callers that need the source list FIRST (to compute per-card targeted
    destinations, analyzer.fill) — the duplicated trace is structurally
    identical, so XLA CSE collapses it."""
    b = state.num_brokers
    s_dim = state.max_replication_factor
    exists = replica_exists(state)
    seg = jnp.where(state.assignment >= 0, state.assignment, b)
    on_source = (jnp.concatenate([source_score, jnp.array([-1.0])])[seg] > 0.0) & exists

    flat_weight = jnp.where(on_source, replica_weight, -jnp.inf).reshape(-1)
    n_flat = flat_weight.shape[0]
    k_src = min(num_sources, n_flat)

    # Source rows must be BROKER-DIVERSE: conflict-free selection admits at
    # most one move per source broker per round (for totals-dependent
    # goals), so a global top-k by weight — which piles onto the few most
    # overloaded brokers — caps accepted moves per round at a handful
    # regardless of k. Mirror the reference's per-broker greedy
    # (AbstractGoal.rebalanceForBroker iterates brokersToBalance, each
    # offering its own sorted replicas): half the rows are the globally
    # heaviest replicas (preserves offline/self-healing priority), half are
    # the best (and second-best) replica of each of the top source brokers.
    quarter = min(k_src // 4, b)
    half = k_src - 2 * quarter            # exact: half + 2*quarter == k_src
    seg_flat = seg.reshape(-1)
    idxs = jnp.arange(n_flat, dtype=jnp.int32)

    g_w, g_idx = jax.lax.top_k(flat_weight, half)
    # Mask the global block's rows out of the per-broker selection so the
    # broker blocks only ADD diversity (on skewed clusters the globally
    # heaviest replicas are exactly the top brokers' best replicas, and a
    # duplicate row wastes its whole k_dst grid slice).
    in_global = jnp.zeros(n_flat + 1, dtype=bool).at[
        jnp.where(jnp.isfinite(g_w), g_idx, n_flat)].set(True)[:n_flat]
    flat_weight_rest = jnp.where(in_global, -jnp.inf, flat_weight)

    def per_broker_best(fw):
        smax = jax.ops.segment_max(fw, seg_flat, num_segments=b + 1)
        is_best = jnp.isfinite(fw) & (fw == smax[seg_flat])
        best = jax.ops.segment_min(jnp.where(is_best, idxs, n_flat),
                                   seg_flat, num_segments=b + 1)
        return smax[:b], best[:b]          # [B] weight, [B] flat idx

    w1, best1 = per_broker_best(flat_weight_rest)
    w2, best2 = per_broker_best(
        jnp.where(idxs == jnp.concatenate(
            [best1, jnp.array([n_flat], jnp.int32)])[seg_flat],
            -jnp.inf, flat_weight_rest))
    b_score = jnp.where(jnp.isfinite(w1), source_score, -jnp.inf)
    tb_score, top_brokers = jax.lax.top_k(b_score, quarter)
    broker_ok = jnp.isfinite(tb_score)
    rows_b1 = jnp.where(broker_ok, best1[top_brokers], n_flat)
    ok_b2 = broker_ok & jnp.isfinite(w2[top_brokers])
    rows_b2 = jnp.where(ok_b2, best2[top_brokers], n_flat)

    top_idx = jnp.concatenate([g_idx, rows_b1, rows_b2])[:k_src]
    src_valid = jnp.concatenate([jnp.isfinite(g_w), broker_ok, ok_b2])[:k_src]
    src_valid &= top_idx < n_flat
    top_idx = jnp.minimum(top_idx, n_flat - 1)
    cand_p = (top_idx // s_dim).astype(jnp.int32)
    cand_s = (top_idx % s_dim).astype(jnp.int32)
    return cand_p, cand_s, src_valid


def generate_candidates(state: ClusterTensors, derived: DerivedState,
                        source_score: jax.Array, dest_score: jax.Array,
                        replica_weight: jax.Array, num_sources: int,
                        num_dests: int, include_leadership: bool,
                        leadership_only: bool = False,
                        extra_dst: "tuple[jax.Array, jax.Array] | None" = None,
                        ) -> "tuple[Candidates, tuple[tuple[int, int], ...]]":
    """Top-k × top-k candidate grid.

    - ``source_score[B]``: how much each broker needs to shed (>0 = source).
    - ``dest_score[B]``: how attractive each broker is as a destination
      (-inf = not eligible).
    - ``replica_weight[P, S]``: which replicas are worth moving (higher =
      try first; the per-goal analogue of SortedReplicas score functions).
    - ``extra_dst``: optional (dst [k_src], ok [k_src]) per-card TARGETED
      destination (Goal.target_dests over the select_sources card list),
      appended as one more column of the move block so each source also
      competes with a destination constructed for it.

    Replica moves: the ``num_sources`` highest-weight replicas living on
    positive-score source brokers × the ``num_dests`` best destinations.
    Leadership: the top leader slots on source brokers × their follower
    slots (dst_broker implied by slot).

    Returns (candidates, layout) where ``layout`` describes the grid blocks
    — [k_src × (k_dst + extra)] moves then [k_l × S] leadership — so the
    selector can do a per-source best-destination reduction before global
    ranking.
    """
    b = state.num_brokers
    s_dim = state.max_replication_factor
    exists = replica_exists(state)
    seg = jnp.where(state.assignment >= 0, state.assignment, b)
    on_source = (jnp.concatenate([source_score, jnp.array([-1.0])])[seg] > 0.0) & exists
    k_src = min(num_sources, exists.size)
    cand_p, cand_s, src_valid = select_sources(state, source_score,
                                               replica_weight, num_sources)

    layout: list[tuple[int, int]] = []
    parts: list[Candidates] = []
    if not leadership_only:
        k_dst = min(num_dests, b)
        _dst_score, dst_idx = jax.lax.top_k(dest_score, k_dst)
        dst_valid = jnp.isfinite(_dst_score)
        cols_dst = jnp.broadcast_to(dst_idx.astype(jnp.int32)[None, :],
                                    (k_src, k_dst))
        cols_ok = jnp.broadcast_to(dst_valid[None, :], (k_src, k_dst))
        if extra_dst is not None:
            t_dst, t_ok = extra_dst
            cols_dst = jnp.concatenate(
                [cols_dst, t_dst.astype(jnp.int32)[:, None]], axis=1)
            cols_ok = jnp.concatenate([cols_ok, t_ok[:, None]], axis=1)
        k_cols = cols_dst.shape[1]
        n = k_src * k_cols
        grid_p = jnp.repeat(cand_p, k_cols)
        grid_s = jnp.repeat(cand_s, k_cols)
        grid_valid = jnp.repeat(src_valid, k_cols) & cols_ok.reshape(-1)
        grid_dst = cols_dst.reshape(-1)
        parts.append(Candidates(
            kind=jnp.zeros(n, dtype=jnp.int8),
            partition=grid_p, src_slot=grid_s, dst_broker=grid_dst,
            dst_slot=jnp.zeros(n, dtype=jnp.int32), valid=grid_valid))
        layout.append((k_src, k_cols))

    if include_leadership or leadership_only:
        # Leadership candidates: for each top source replica that IS a
        # leader, try every other slot.
        lead_mask = is_leader_slot(state)
        lead_weight = jnp.where(on_source & lead_mask, replica_weight, -jnp.inf)
        flat_lw = lead_weight.reshape(-1)
        k_l = min(num_sources, flat_lw.shape[0])
        top_lw, top_lidx = jax.lax.top_k(flat_lw, k_l)
        lp = (top_lidx // s_dim).astype(jnp.int32)
        l_valid = jnp.isfinite(top_lw)
        n = k_l * s_dim
        grid_p = jnp.repeat(lp, s_dim)
        grid_valid = jnp.repeat(l_valid, s_dim)
        grid_dslot = jnp.tile(jnp.arange(s_dim, dtype=jnp.int32), k_l)
        parts.append(Candidates(
            kind=jnp.ones(n, dtype=jnp.int8),
            partition=grid_p,
            src_slot=jnp.zeros(n, dtype=jnp.int32),
            dst_broker=jnp.zeros(n, dtype=jnp.int32),
            dst_slot=grid_dslot, valid=grid_valid))
        layout.append((k_l, s_dim))

    return Candidates(
        kind=jnp.concatenate([c.kind for c in parts]),
        partition=jnp.concatenate([c.partition for c in parts]),
        src_slot=jnp.concatenate([c.src_slot for c in parts]),
        dst_broker=jnp.concatenate([c.dst_broker for c in parts]),
        dst_slot=jnp.concatenate([c.dst_slot for c in parts]),
        valid=jnp.concatenate([c.valid for c in parts]),
    ), tuple(layout)


def _exclusive_group_prefix(keys: "tuple[jax.Array, ...]",
                            values: jax.Array) -> jax.Array:
    """For each row i: sum of ``values[j]`` over EARLIER rows j < i whose
    key tuple equals row i's — the per-group exclusive prefix sum, by one
    lexicographic sort on (keys..., index) + a cumsum + a group-base
    gather: O(m log m) instead of the [m, m] mask matmul. Key tuples
    avoid composite-integer keys (int64 is unavailable without
    jax_enable_x64). ``values`` is [m, C]."""
    m = values.shape[0]
    # np.lexsort semantics: LAST key is primary; appending the index makes
    # the order total, so within a group rows appear in index order.
    perm = jnp.lexsort((jnp.arange(m),) + tuple(reversed(keys)))
    v_sorted = values[perm]
    cs_prev = jnp.concatenate(
        [jnp.zeros((1, values.shape[1]), values.dtype),
         jnp.cumsum(v_sorted, axis=0)[:-1]])
    is_start = jnp.zeros(m, dtype=bool).at[0].set(True)
    for k in keys:
        ks = k[perm]
        is_start = is_start | jnp.concatenate(
            [jnp.array([True]), ks[1:] != ks[:-1]])
    # lax.cummax, not jnp.maximum.accumulate: jnp ufunc objects carry no
    # .accumulate under jitted tracing on this jax line.
    start_pos = jax.lax.cummax(
        jnp.where(is_start, jnp.arange(m), 0), axis=0)
    excl = cs_prev - cs_prev[start_pos]
    return jnp.zeros_like(values).at[perm].set(excl)


def attach_cumulative_segments(sub: CandidateDeltas, considered: jax.Array,
                               pot_delta: jax.Array, lbi_delta: jax.Array,
                               ) -> tuple[CandidateDeltas, jax.Array]:
    """O(m log m) ``attach_cumulative``: per-key exclusive prefix sums via
    sorted segments instead of [m, m] mask matmuls. Numerically the sums
    run in sorted order rather than index order — equal up to f32
    reassociation — and the m² → m log m change is what makes SELECTION
    widths beyond ~2k affordable (the pairwise matmul is the width
    bottleneck of the wide-batch grids at 7k scale)."""
    f32 = jnp.float32
    m = sub.partition.shape[0]
    rep = sub.replica_delta.astype(f32)
    lead = sub.leader_delta.astype(f32)
    r = sub.load_delta.shape[1]
    src_vals = jnp.concatenate(
        [sub.load_delta, rep[:, None], lead[:, None]], axis=1)   # [m, R+2]
    dst_vals = jnp.concatenate(
        [sub.load_delta, rep[:, None], lead[:, None], pot_delta[:, None],
         lbi_delta[:, None]], axis=1)                            # [m, R+4]
    cons = considered.astype(f32)[:, None]
    src_out = _exclusive_group_prefix((sub.src_broker,), src_vals * cons)
    dst_out = _exclusive_group_prefix((sub.dst_broker,), dst_vals * cons)
    topic_vals = jnp.stack([rep, lead], axis=1) * cons
    st_out = _exclusive_group_prefix((sub.src_broker, sub.topic), topic_vals)
    dt_out = _exclusive_group_prefix((sub.dst_broker, sub.topic), topic_vals)

    # has_earlier: any earlier CONSIDERED row touching either of my
    # brokers in either role. Per-broker first-touch rank via the same
    # sorted-group machinery (a dense [B] scatter would need a traced
    # broker bound for its shape): each row contributes its (src, rank)
    # and (dst, rank) entries; within a sorted group the first entry IS
    # the min rank, broadcast group-wide through the start-position
    # gather and scattered back to entry order.
    idx = jnp.arange(m, dtype=jnp.int32)
    rank_eff = jnp.where(considered, idx, m)
    keys2 = jnp.concatenate([sub.src_broker, sub.dst_broker])
    ranks2 = jnp.concatenate([rank_eff, rank_eff])
    perm2 = jnp.lexsort((jnp.arange(2 * m), ranks2, keys2))
    k_sorted = keys2[perm2]
    is_start = jnp.concatenate(
        [jnp.array([True]), k_sorted[1:] != k_sorted[:-1]])
    start_pos = jax.lax.cummax(
        jnp.where(is_start, jnp.arange(2 * m), 0), axis=0)
    group_min = ranks2[perm2][start_pos]
    entry_min = jnp.zeros(2 * m, jnp.int32).at[perm2].set(group_min)
    has_earlier = (entry_min[:m] < idx) | (entry_min[m:] < idx)

    return dataclasses.replace(
        sub,
        pre_src_load=src_out[:, :r],
        pre_dst_load=dst_out[:, :r],
        pre_src_count=src_out[:, r],
        pre_dst_count=dst_out[:, r],
        pre_src_leaders=src_out[:, r + 1],
        pre_dst_leaders=dst_out[:, r + 1],
        pre_src_topic_count=st_out[:, 0],
        pre_dst_topic_count=dt_out[:, 0],
        pre_src_topic_leaders=st_out[:, 1],
        pre_dst_pot=dst_out[:, r + 2],
        pre_dst_lbi=dst_out[:, r + 3],
    ), has_earlier


# Cumulative pre-delta implementation: "segment" (O(m log m) sort-based)
# or "matmul" ([m, m] pairwise masks — the MXU-friendly form and the
# equivalence oracle). Default is BACKEND-AWARE, decided lazily at trace
# time (the backend is not known at import): segment on CPU (measured
# −13% TopicReplica round cost at 7k), matmul on accelerators (the MXU
# eats [m, m] matmuls; device-side sorts are comparatively slow and the
# segment form is unmeasured on the chip). CC_ATTACH overrides.
def _attach_impl() -> str:
    impl = os.environ.get("CC_ATTACH")
    if impl:
        return impl
    return "segment" if jax.default_backend() == "cpu" else "matmul"


def attach_cumulative(sub: CandidateDeltas, considered: jax.Array,
                      pot_delta: jax.Array, lbi_delta: jax.Array,
                      ) -> tuple[CandidateDeltas, jax.Array]:
    """Fill the ``pre_*`` fields of a RANK-ORDERED candidate batch: for each
    candidate i, the summed effect of every considered candidate j < i on
    i's src/dst brokers (pairwise masks + matmuls over the small selected
    batch — [m, m] with m ≤ a few hundred).

    ``considered[j]`` marks candidates whose effect must be assumed applied
    (passed scoring + partition dedupe). Including candidates that a later
    acceptance recheck rejects only OVERCOUNTS inflow/outflow — the checks
    get stricter, never looser, so the relaxation stays sound.
    ``pot_delta``/``lbi_delta`` are the per-candidate potential-NW-out and
    leader-bytes-in transfer scalars (computed by the caller so this stays
    free of per-partition state gathers — shard-safe).

    Returns (sub with pre fields, has_earlier[m]) where ``has_earlier``
    marks candidates sharing a src or dst broker with an earlier considered
    candidate (the first candidate per broker keeps single-candidate
    acceptance semantics)."""
    if _attach_impl() == "segment":
        return attach_cumulative_segments(sub, considered, pot_delta,
                                          lbi_delta)
    m = sub.partition.shape[0]
    idx = jnp.arange(m)
    earlier = (idx[:, None] > idx[None, :]) & considered[None, :]
    same_dst = earlier & (sub.dst_broker[:, None] == sub.dst_broker[None, :])
    same_src = earlier & (sub.src_broker[:, None] == sub.src_broker[None, :])
    cross_sd = earlier & (sub.src_broker[:, None] == sub.dst_broker[None, :])
    cross_ds = earlier & (sub.dst_broker[:, None] == sub.src_broker[None, :])
    same_topic = sub.topic[:, None] == sub.topic[None, :]

    f32 = jnp.float32
    rep = sub.replica_delta.astype(f32)
    lead = sub.leader_delta.astype(f32)

    # One [m, m] matmul per MASK with the value columns stacked, instead of
    # one matmul per field: at wide-batch m (~2k) the pairwise matmuls are
    # a measurable slice of a round on the host backend, and each output
    # column depends only on its own value column, so stacking is exact.
    r = sub.load_delta.shape[1]
    src_vals = jnp.concatenate(
        [sub.load_delta, rep[:, None], lead[:, None]], axis=1)   # [m, R+2]
    dst_vals = jnp.concatenate(
        [sub.load_delta, rep[:, None], lead[:, None], pot_delta[:, None],
         lbi_delta[:, None]], axis=1)                            # [m, R+4]
    src_out = same_src.astype(f32) @ src_vals
    dst_out = same_dst.astype(f32) @ dst_vals
    st_out = (same_src & same_topic).astype(f32) @ jnp.stack([rep, lead], axis=1)
    dt_count = ((same_dst & same_topic).astype(f32) @ rep[:, None])[:, 0]

    has_earlier = (same_dst | same_src | cross_sd | cross_ds).any(axis=1)
    return dataclasses.replace(
        sub,
        pre_src_load=src_out[:, :r],
        pre_dst_load=dst_out[:, :r],
        pre_src_count=src_out[:, r],
        pre_dst_count=dst_out[:, r],
        pre_src_leaders=src_out[:, r + 1],
        pre_dst_leaders=dst_out[:, r + 1],
        pre_src_topic_count=st_out[:, 0],
        pre_dst_topic_count=dt_count,
        pre_src_topic_leaders=st_out[:, 1],
        pre_dst_pot=dst_out[:, r + 2],
        pre_dst_lbi=dst_out[:, r + 3],
    ), has_earlier
