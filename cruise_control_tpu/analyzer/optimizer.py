"""GoalOptimizer: run the goal chain by priority, collect stats, diff
proposals.

Reference parity: analyzer/GoalOptimizer.java:435-524 (optimizations():
iterate goals in priority order, each mutating the shared model under the
acceptance of all previously optimized goals; per-goal stats + durations;
diff initial vs final into proposals) and OptimizerResult.java.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time
from typing import Sequence

import jax.numpy as jnp
import numpy as np

from ..config.abstract_config import resolve_class
from ..config.cruise_control_config import CruiseControlConfig
from ..model.stats import ClusterModelStats, cluster_stats
from ..model.tensors import ClusterMeta, ClusterTensors
from .chain import optimize_chain, optimize_goal_in_chain
from .constraint import BalancingConstraint, OptimizationOptions
from .goals import ALL_GOALS
from .goals.base import Goal
from .proposals import ExecutionProposal, diff_proposals
from .search import ExclusionMasks, SearchConfig

LOG = logging.getLogger(__name__)

# Balancedness score weights (KafkaCruiseControlUtils.java:831-856): each
# priority level weighs priorityWeight× the next, hard goals weigh
# strictnessWeight×, normalized to MAX_BALANCEDNESS_SCORE.
MAX_BALANCEDNESS_SCORE = 100.0


@dataclasses.dataclass
class GoalResult:
    name: str
    is_hard: bool
    succeeded: bool
    rounds: int
    moves_applied: int
    residual_violation: float
    duration_s: float
    violated_before: bool
    swaps_applied: int = 0


@dataclasses.dataclass
class OptimizerResult:
    proposals: list[ExecutionProposal]
    goal_results: list[GoalResult]
    stats_before: ClusterModelStats
    stats_after: ClusterModelStats
    violated_goals_before: list[str]
    violated_goals_after: list[str]
    balancedness_before: float
    balancedness_after: float
    duration_s: float

    def summary(self) -> dict:
        return {
            "num_proposals": len(self.proposals),
            "num_leadership_only": sum(p.is_leadership_only for p in self.proposals),
            "violated_goals_before": self.violated_goals_before,
            "violated_goals_after": self.violated_goals_after,
            "balancedness_before": round(self.balancedness_before, 3),
            "balancedness_after": round(self.balancedness_after, 3),
            "duration_s": round(self.duration_s, 3),
            "goals": {g.name: {"rounds": g.rounds, "moves": g.moves_applied,
                               "violation": round(g.residual_violation, 4)}
                      for g in self.goal_results},
        }


def goals_by_priority(cfg: CruiseControlConfig,
                      goal_names: Sequence[str] | None = None) -> list[Goal]:
    """Instantiate the goal chain (KafkaCruiseControlUtils.goalsByPriority:
    config reflection over dotted paths; short names resolve through the
    registry)."""
    specs = list(goal_names) if goal_names else cfg.get_list("goals")
    goals = []
    for spec in specs:
        short = spec.rsplit(".", 1)[-1]
        cls = ALL_GOALS.get(short)
        if cls is None:
            cls = resolve_class(spec)
        goals.append(cls())
    return goals


def balancedness_score(goals: Sequence[Goal], violated: set[str],
                       priority_weight: float = 1.1,
                       strictness_weight: float = 1.5) -> float:
    """100 minus the normalized weighted cost of violated goals
    (GoalViolationDetector.refreshBalancednessScore:282-287)."""
    weights = []
    for i, g in enumerate(goals):
        w = priority_weight ** (len(goals) - 1 - i)
        if g.is_hard:
            w *= strictness_weight
        weights.append(w)
    total = sum(weights) or 1.0
    cost = sum(w for g, w in zip(goals, weights) if g.name in violated)
    return MAX_BALANCEDNESS_SCORE * (1.0 - cost / total)


def _apportioned_goal_results(goal_chain: Sequence[Goal], infos: list[dict],
                              chain_s: float) -> list[GoalResult]:
    """GoalResults from whole-chain kernel stats. Per-goal wall-clock cannot
    be measured inside one dispatch; the chain time is apportioned by each
    goal's share of search rounds (equal split when no goal ran).
    violated_before follows the reference (GoalOptimizer.java:450-482): a
    goal was violated BEFORE optimization iff it had work to do when its
    turn came, or it failed."""
    total_rounds = sum(info["rounds"] for info in infos) or None
    return [GoalResult(
        name=g.name, is_hard=g.is_hard, succeeded=info["succeeded"],
        rounds=info["rounds"], moves_applied=info["moves_applied"],
        residual_violation=info["residual_violation"],
        duration_s=chain_s * (info["rounds"] / total_rounds
                              if total_rounds else 1 / len(infos)),
        violated_before=info["violated_on_entry"] or not info["succeeded"],
        swaps_applied=info.get("swaps_applied", 0))
        for g, info in zip(goal_chain, infos)]


def _record_goal_spans(tracer, goal_results: Sequence[GoalResult],
                       search_cfg: SearchConfig) -> None:
    """Per-goal spans for the single-dispatch paths: the whole chain runs
    in one XLA execution, so the goals' spans cannot be opened live —
    they are attached after the fact with the same apportioned durations
    GoalResult carries (attributes mark them as such)."""
    for r in goal_results:
        tracer.record_span(
            "goal.solve", r.duration_s, goal=r.name, rounds=r.rounds,
            moves_applied=r.moves_applied, succeeded=r.succeeded,
            candidates=search_cfg.num_sources * search_cfg.num_dests,
            apportioned=True)


# Goals whose direct-transport arm stays ahead of greedy even at sparse
# geometry (bench --transport, ROADMAP 2d): TR's [T, B] cell plane keeps
# enough surplus per cell for the fractional plan to pay for itself,
# while Replica/LeaderReplica at the same density solve faster under
# deficit-sized greedy (the documented honest negative — 2 reverts).
_SPARSE_DIRECT_GOALS = ("TopicReplicaDistributionGoal",)


def replica_density(state, num_topics: int) -> float:
    """Replicas per (topic, broker) transport cell — the geometry that
    decides the per-goal direct-vs-greedy choice. The transport plans
    shed/fill whole cells; below ~2 replicas/cell most cells cannot
    donate without emptying, so count goals spend their sweeps on
    stranded movers that greedy would simply route around."""
    cells = max(1, int(num_topics) * int(state.num_brokers))
    slots = int(state.assignment.shape[-1])
    return float(int(state.num_partitions) * slots) / float(cells)


def direct_goal_choice(density: float,
                       threshold: float) -> "tuple[str, ...] | None":
    """Per-goal density-aware path choice (ROADMAP 2d): None = every
    direct-eligible goal keeps the direct arm (dense regime / choice
    disabled); at sparse geometry only ``_SPARSE_DIRECT_GOALS`` keep it
    and the rest take deficit-sized greedy."""
    if threshold <= 0 or density >= threshold:
        return None
    return _SPARSE_DIRECT_GOALS


class GoalOptimizer:
    """Facade over the batched chain search (GoalOptimizer.java:65).

    ``mesh``: a 1-D ``jax.sharding.Mesh`` to run the solver SPMD over
    multiple chips (partition axis sharded, collectives over ICI). Pass
    ``mesh="auto"`` to use all local devices when more than one is present.
    The reference's scale mechanism here is a precompute thread pool
    (GoalOptimizer.java:112-119); the TPU-native one is the mesh."""

    def __init__(self, config: CruiseControlConfig | None = None,
                 mesh=None):
        self._config = config or CruiseControlConfig()
        self._constraint = BalancingConstraint.from_config(self._config)
        self._cand_budget = self._config.get_int("solver.candidates.per.round")
        # An EXPLICITLY configured candidate budget is a hard bound (the
        # operator's memory knob); the default value means "auto-scale with
        # cluster size".
        self._cand_budget_explicit = \
            "solver.candidates.per.round" in self._config.originals()
        self._moves_base = self._config.get_int("solver.moves.per.round")
        self._max_rounds = self._config.get_int("max.solver.rounds")
        self._priority_weight = self._config.get_double("goal.balancedness.priority.weight")
        self._strictness_weight = self._config.get_double("goal.balancedness.strictness.weight")
        self._fused_chain = self._config.get_boolean("solver.chain.fused")
        self._fused_max_brokers = self._config.get_int(
            "solver.fused.chain.max.brokers")
        self._dispatch_rounds = self._config.get_int(
            "solver.dispatch.max.rounds")
        self._dispatch_target_s = self._config.get_double(
            "solver.dispatch.target.seconds")
        self._megastep_donate = self._config.get_boolean(
            "solver.megastep.donate")
        self._async_readback = self._config.get_boolean(
            "solver.dispatch.async.readback")
        self._deficit_moves_cap = self._config.get_int(
            "solver.deficit.moves.cap")
        self._direct_enabled = self._config.get_boolean(
            "solver.direct.assignment.enabled")
        self._direct_max_sweeps = self._config.get_int(
            "solver.direct.max.sweeps")
        self._direct_sparse_margin = self._config.get_double(
            "solver.direct.sparse.margin.frac")
        self._direct_sparse_salt = self._config.get_string(
            "solver.direct.sparse.rounding.salt")
        self._direct_sparse_threshold = self._config.get_double(
            "solver.direct.density.sparse.threshold")
        # Device-sharded megabatch (round 23): shard the CLUSTER axis of
        # fleet solves across the mesh when one is attached.
        self._shard_enabled = self._config.get_boolean(
            "fleet.shard.enabled")
        # Fingerprint goal skipping (round 18): ONE batched stats program
        # snapshots every goal's entry violation before the bounded
        # per-goal loop; goals with nothing to do consume zero dispatches
        # (byte-identical — a violation-free goal applies nothing).
        self._fingerprint_skip = self._config.get_boolean(
            "solver.fingerprint.skip.enabled")
        # Prewarm shape registry (round 18, warmstart.ensure_prewarm):
        # when attached, every solve records its padded tensor signature
        # so a FRESH process can compile the whole per-shape kernel set
        # in a background thread before its first request.
        self._shape_registry = None
        # Adaptive dispatch controllers PERSIST across optimization passes,
        # keyed by MODEL SHAPE: per-round cost is a property of the
        # cluster shape, so the budget learned on one pass carries to the
        # next pass of the SAME shape — the fleet pacer's repeated
        # precomputes skip the relearning ramp — while a fleet-shared
        # optimizer can never apply a big budget learned on a cheap small
        # cluster to a 10x-larger one's first dispatch (watchdog risk).
        # The shape key is the padded bucket shape, so the set stays tiny.
        import threading
        self._controllers: dict = {}
        self._controllers_lock = threading.Lock()
        self._dispatch_stats = None
        self._pass_seq = 0
        # Exact per-caller attribution on a shared optimizer: each pass
        # also records (seq, stats) thread-locally, so a caller whose
        # solve runs synchronously on its own thread (the fleet pacer)
        # can read back THE pass it ran, immune to passes other threads
        # start concurrently or to its request being cache-served.
        self._tls = threading.local()
        if mesh == "auto":
            import jax

            from ..parallel.mesh import make_mesh
            mesh = make_mesh() if len(jax.devices()) > 1 else None
        self._mesh = mesh if (mesh is not None
                              and mesh.devices.size > 1) else None
        self._devices_used = int(self._mesh.devices.size) if self._mesh else 1

    @property
    def mesh(self):
        return self._mesh

    def solver_devices(self) -> int:
        """Device count the LAST optimization pass actually ran on (bench
        reporting — the mesh falls back to single-device when the partition
        axis does not divide it, and reporting the mesh size then would
        corrupt the vs-baseline comparison)."""
        return self._devices_used

    def last_dispatch_stats(self) -> dict:
        """Dispatch accounting of the LAST optimization pass (bench/CI
        surface): dispatch_count, rounds_per_dispatch_p50, donated and
        speculative tallies. Empty dict before any pass. On a fleet-shared
        optimizer this reflects the most recently STARTED pass — callers
        that need per-job attribution (the pacer's precompute job) must
        read it on the solving thread immediately after their own solve
        returns, before another thread can start a pass — and compare
        ``pass_seq()`` across the call to detect that no new pass ran at
        all (a cache-served request must not claim another pass's
        stats)."""
        return self._dispatch_stats.as_dict() if self._dispatch_stats \
            else {}

    def pass_seq(self) -> int:
        """Monotonic count of optimization passes STARTED on this
        optimizer. Pairs with last_dispatch_stats(): a caller whose
        request may be served from a proposal cache snapshots the seq
        before and after — unchanged seq means no solve ran, so the
        current stats belong to some other caller's pass."""
        return self._pass_seq

    def thread_pass_seq(self) -> int:
        """Seq of the last pass run ON THE CALLING THREAD (0 if none).
        Unlike pass_seq() this cannot be advanced by another thread's
        pass, so snapshot-before / compare-after brackets exactly the
        caller's own solves."""
        last = getattr(self._tls, "last_pass", None)
        return last[0] if last else 0

    def thread_dispatch_stats(self) -> dict:
        """Dispatch accounting of the last pass run ON THE CALLING
        THREAD — exact attribution for embedders (the fleet pacer) whose
        solve happens synchronously inside their call, regardless of
        what passes other threads start meanwhile. {} if this thread
        never ran one."""
        last = getattr(self._tls, "last_pass", None)
        return last[1].as_dict() if last else {}

    def _controller_pair(self, state: ClusterTensors, batch: int = 0,
                         devices: int = 1):
        """(narrow, wide) persistent AdaptiveDispatch pair for this model
        shape (created on first use; lock-guarded — facade request
        threads and the fleet worker may solve concurrently).

        ``batch`` > 0 keys a MEGABATCH width into the shape: a batched
        round costs ~occupancy× a single-cluster round on a busy device,
        so the budget learned on solo solves of this shape must not carry
        onto the first 8-wide fleet dispatch (and vice versa) — same
        cost-class discipline as the narrow/wide split.

        ``devices`` keys the mesh size of the SHARDED megabatch (round
        23): a width-64 batch over 4 devices costs a width-16 round per
        step, not a width-64 one, so its budget must not mix with the
        single-device batch=64 controller's (the controller-keying
        contract in DESIGN.md).

        Only the dict lookup is locked: the controllers themselves are
        deliberately unsynchronized. Two same-shape solves running
        concurrently contend for the device, inflate each other's
        observed per-dispatch wall-clock, and can transiently halve the
        shared budget — accepted, because the error is bounded (k never
        leaves [1, max]), self-correcting (k doubles again on the next
        on-target dispatch of a solo pass), and affects only dispatch
        boundaries, never the trajectory. A lock around observe/budget
        would serialize readbacks across solves on the hot path to
        protect a heuristic."""
        from .chain import AdaptiveDispatch
        key = (state.num_partitions, state.num_brokers, batch, devices)
        # ccsa: ok[CCSA007] PR 5 tolerance, machine-readable: registry
        # lookups locked below; the AdaptiveDispatch values are
        # deliberately unsynchronized — bounded (k stays in [1, max]),
        # self-correcting, dispatch-boundary-only (see docstring)
        with self._controllers_lock:
            pair = self._controllers.get(key)
            if pair is None:
                pair = (AdaptiveDispatch(max(1, self._dispatch_rounds),
                                         self._dispatch_target_s),
                        AdaptiveDispatch(max(1, self._dispatch_rounds),
                                         self._dispatch_target_s))
                self._controllers[key] = pair
        return pair

    def _megastep_config(self, num_brokers: int,
                         density: "float | None" = None):
        """Resolve the megastep knobs for one pass. Deficit-aware count-
        goal sizing shares the wide-batch regime gate: below it the fused
        whole-chain kernel is the production path and the bounded drivers
        must walk its exact trajectory (the cross-path parity contract).

        ``density`` (ROADMAP 2d, round 23): the model's replica density —
        below ``solver.direct.density.sparse.threshold`` the per-goal
        path choice keeps the direct arm only for the goals measured
        faster there (see ``direct_goal_choice``). None skips the choice
        (all direct-eligible goals take the direct arm)."""
        from .chain import MegastepConfig
        threshold = self._config.get_int("solver.wide.batch.min.brokers")
        in_regime = threshold > 0 and num_brokers >= threshold
        chosen = None
        if density is not None:
            chosen = direct_goal_choice(density,
                                        self._direct_sparse_threshold)
        return MegastepConfig(
            donate=self._megastep_donate,
            async_readback=self._async_readback,
            deficit_moves_cap=self._deficit_moves_cap if in_regime else 0,
            # Direct-assignment transport shares the wide-regime gate: it
            # REPLACES deficit-sized greedy there; below the gate the
            # greedy path is kept byte-identical (the parity pins).
            direct_assignment=self._direct_enabled and in_regime,
            direct_max_sweeps=self._direct_max_sweeps,
            direct_sparse_margin=self._direct_sparse_margin,
            direct_sparse_salt=self._direct_sparse_salt,
            direct_goals=chosen)

    def deficit_sizing_active(self, num_brokers: int) -> bool:
        """Whether a SERIAL solve of this broker count would run
        deficit-aware count-goal sizing. The megabatch path structurally
        disables it (the grid cannot specialize to one batch member), so
        callers with a choice of path — the facade's fleet-wired
        ``_optimize`` seam — must keep the serial path in this regime or
        silently change solution quality vs a standalone deployment."""
        return self._megastep_config(num_brokers).deficit_moves_cap > 0

    @property
    def constraint(self) -> BalancingConstraint:
        return self._constraint

    def search_config(self, state: ClusterTensors) -> SearchConfig:
        """Scale-aware candidate pruning (replaces round-2's fixed
        num_dests=16, which capped broker-deduped goals at ~16 accepted
        moves per round regardless of cluster size — VERDICT r2 weak #3).

        The grid budget grows with broker count so per-round parallelism
        tracks the cluster: conflict-free selection admits at most
        ~min(num_sources, num_dests, B/2) moves per round for goals whose
        acceptance reads per-broker totals, so num_dests must scale with B
        or round counts scale as O(moves_needed / 16). Wide grids are
        near-free on TPU (one fused kernel); round count is the scarce
        resource."""
        b = state.num_brokers
        budget = self._cand_budget if self._cand_budget_explicit \
            else max(self._cand_budget, min(131_072, b * 64))
        num_dests = max(16, min(512, b // 4))
        if self._cand_budget_explicit:
            # Honor the operator's budget as a bound on the move grid:
            # sources × dests ≤ budget (floors drop to the minimum viable).
            num_dests = min(num_dests, max(4, budget // 16))
            num_sources = max(16, min(1024, budget // num_dests))
        else:
            # Batch width is a QUALITY knob, not just a speed knob
            # (measured at 1k/100k, seed 42): 256 sources → 1,142 rounds,
            # balancedness 86.0; 500 → 644 rounds but 82.7; 1,000 → 341
            # rounds but 74.5. Wider joint batches mean fewer re-scoring
            # points per move, and the coarser layout the early count
            # goals lock in is then defended by their acceptance against
            # the later resource-distribution goals' fixes. Keep the
            # measured-best grid; round count is bought with dispatch
            # amortization (AdaptiveDispatch) instead.
            num_sources = max(64, min(1024, budget // num_dests))
        moves = max(self._moves_base, min(1024, b // 2))
        return SearchConfig(num_sources=num_sources, num_dests=num_dests,
                            moves_per_round=moves,
                            max_rounds=self._max_rounds)

    # -- entry snapshots (round 19: forecast scoring + warm pre-check) -----
    def goal_entry_stats(self, state: ClusterTensors, meta: ClusterMeta,
                         goals: Sequence[Goal] | None = None,
                         options: OptimizationOptions | None = None,
                         ) -> tuple[list[Goal], np.ndarray, np.ndarray, int]:
        """Every goal's entry (violation, objective) plus the offline
        count on ``state`` in ONE batched device program — the round-18
        ``chain_all_goal_stats`` snapshot as a public seam. Two callers:
        the predictive detector scores the forecaster's PROJECTED model
        through it, and the facade's warm-band pre-check scores the warm
        seed against the drifted loads before committing to the full
        chain. Returns (resolved chain, [G] violations, [G] objectives,
        offline replicas)."""
        options = options or OptimizationOptions()
        chain = list(goals) if goals is not None \
            else goals_by_priority(self._config)
        chain = self._resolve_broker_sets(chain, meta)
        masks = self._masks(state, meta, options)
        from .chain import chain_all_goal_stats
        av, ao, aoff = chain_all_goal_stats(
            state, tuple(chain), self._constraint, meta.num_topics, masks)
        return chain, np.asarray(av), np.asarray(ao), int(aoff)

    def balancedness_of(self, chain: Sequence[Goal],
                        violated: "set[str] | Sequence[str]") -> float:
        """The 0..100 balancedness score of a violated-goal set under
        this optimizer's configured weights (the same formula the
        detector and OptimizerResult use)."""
        return balancedness_score(list(chain), set(violated),
                                  self._priority_weight,
                                  self._strictness_weight)

    def _masks(self, state: ClusterTensors, meta: ClusterMeta,
               options: OptimizationOptions) -> ExclusionMasks:
        topic_mask = None
        if options.excluded_topics:
            excluded = set(options.excluded_topics)
            topic_mask = jnp.asarray(np.array(
                [t in excluded for t in meta.topic_names]
                + [False] * (state.num_partitions - len(meta.topic_names)), dtype=bool))
        rm_mask = None
        if options.excluded_brokers_for_replica_move:
            idx = {bid: i for i, bid in enumerate(meta.broker_ids)}
            m = np.zeros(state.num_brokers, dtype=bool)
            for bid in options.excluded_brokers_for_replica_move:
                if bid in idx:
                    m[idx[bid]] = True
            rm_mask = jnp.asarray(m)
        ld_mask = None
        if options.excluded_brokers_for_leadership:
            idx = {bid: i for i, bid in enumerate(meta.broker_ids)}
            m = np.zeros(state.num_brokers, dtype=bool)
            for bid in options.excluded_brokers_for_leadership:
                if bid in idx:
                    m[idx[bid]] = True
            ld_mask = jnp.asarray(m)
        return ExclusionMasks(excluded_topics=topic_mask,
                              excluded_replica_move_brokers=rm_mask,
                              excluded_leadership_brokers=ld_mask)

    def _widen(self, search_cfg: SearchConfig,
               num_brokers: int) -> SearchConfig:
        """The wide-batch grid: sources x solver.wide.batch.source.multiplier
        (default 8), 2x moves — floored at the base config so an
        operator-raised solver.moves.per.round can never make the "wide"
        config narrower than the narrow one. Wide sources are additionally
        capped at the BROKER count: conflict-free selection admits at most
        ~B/2 same-round moves, so width beyond ~B only inflates per-round
        cost (measured: at 1k brokers 2048-wide rounds cost more wall-clock
        than the extra rounds they save; at 7k they cut total rounds 28%
        at identical quality)."""
        mult = self._config.get_int("solver.wide.batch.source.multiplier")
        # The width cap bounds SELECTION size m = max(moves, sources) too;
        # with the O(m log m) segment cumulative (candidates.py) the old
        # m² matmul ceiling no longer binds it — the cap stays a measured
        # quality/throughput knob (CC_WIDE_CAP for experiments).
        cap = int(os.environ.get("CC_WIDE_CAP", "2048"))
        return dataclasses.replace(
            search_cfg,
            num_sources=max(search_cfg.num_sources,
                            min(cap, search_cfg.num_sources * mult,
                                num_brokers)),
            moves_per_round=max(search_cfg.moves_per_round,
                                min(cap, search_cfg.moves_per_round * 2)))

    def _wide_config(self, search_cfg: SearchConfig,
                     goal_chain: Sequence[Goal],
                     num_brokers: int) -> SearchConfig | None:
        """The widened grid for Goal.prefers_wide_batches goals on the
        bounded path, or None when out of regime. Source-limited late-chain
        goals cut their round count ~4x at measured-identical quality
        (TopicReplicaDistribution at 1k/100k: 482 -> 106 rounds, same
        balancedness and violated set; one extra compile of the chain
        kernels at the wide shape)."""
        threshold = self._config.get_int("solver.wide.batch.min.brokers")
        if threshold <= 0 or num_brokers < threshold \
                or not any(g.prefers_wide_batches for g in goal_chain):
            return None
        return self._widen(search_cfg, num_brokers)

    def _resolve_broker_sets(self, goal_chain: list[Goal],
                             meta: ClusterMeta) -> list[Goal]:
        """Bind broker→broker-set ids into any BrokerSetAwareGoal that has
        none: the configured mapping policy
        (replica.to.broker.set.mapping.policy.class, called with
        (config, broker_ids) — BrokerSetResolutionHelper), else the
        brokerSets.json file resolver (broker.set.config.file)."""
        from .goals.broker_set import BrokerSetAwareGoal, broker_sets_from_file
        if not any(isinstance(g, BrokerSetAwareGoal) and not g.broker_sets
                   for g in goal_chain):
            return goal_chain
        sets: tuple[int, ...] | None = None
        policy = self._config.get("replica.to.broker.set.mapping.policy.class")
        if policy:
            cls = resolve_class(policy) if isinstance(policy, str) else policy
            mapper = cls() if isinstance(cls, type) else cls
            sets = tuple(mapper(self._config, list(meta.broker_ids)))
        else:
            import os
            path = self._config.get("broker.set.config.file")
            if path and os.path.exists(path):
                sets = broker_sets_from_file(path, list(meta.broker_ids))
        if sets is None:
            # The operator put BrokerSetAwareGoal in the chain but no
            # mapping resolves — failing loud beats a vacuous constraint
            # (empty sets = one implicit cluster-wide set, which would let
            # replicas cross broker-set boundaries silently).
            raise ValueError(
                "BrokerSetAwareGoal is configured but no broker-set mapping "
                "is available: set replica.to.broker.set.mapping.policy.class "
                f"or point broker.set.config.file at an existing file "
                f"(currently {self._config.get('broker.set.config.file')!r})")
        return [dataclasses.replace(g, broker_sets=sets)
                if isinstance(g, BrokerSetAwareGoal) and not g.broker_sets
                else g for g in goal_chain]

    def optimizations(self, state: ClusterTensors, meta: ClusterMeta,
                      goals: Sequence[Goal] | None = None,
                      options: OptimizationOptions | None = None,
                      initial_state: ClusterTensors | None = None,
                      ) -> tuple[ClusterTensors, OptimizerResult]:
        """Run the goal chain; returns (final_state, OptimizerResult).

        ``initial_state`` (round 18 warm starts): the TRUE current model
        when ``state`` is a warm-seeded search start — the proposal
        diff, stats_before, and the before picture
        (violated_goals_before / balancedness_before, from one batched
        violation snapshot of the true initial — per-goal violations at
        chain start rather than the serial path's at-its-turn reading)
        are computed against it, so results always describe reality,
        never the previous target."""
        from ..utils.flight_recorder import FLIGHT
        from ..utils.progress import step
        from ..utils.tracing import TRACER
        from ..utils.xla_telemetry import shape_scope
        step("OptimizationForGoalChain")
        # seq anticipates the increment inside _optimizations_traced (the
        # one place _pass_seq advances), so the flight record and
        # pass_seq()/thread_pass_seq() agree on the pass's identity.
        with TRACER.span("analyzer.optimize",
                         num_partitions=state.num_partitions,
                         num_brokers=state.num_brokers) as _opt_span, \
                shape_scope(state.num_partitions, state.num_brokers), \
                FLIGHT.pass_scope(
                    seq=self._pass_seq + 1,
                    shape=(state.num_partitions,
                           state.num_brokers)) as flight_pass:
            return self._optimizations_traced(
                state, meta, goals, options, _opt_span, flight_pass,
                t_start=time.time(), initial_state=initial_state)

    def _optimizations_traced(self, state: ClusterTensors, meta: ClusterMeta,
                              goals: Sequence[Goal] | None,
                              options: OptimizationOptions | None,
                              _opt_span, flight_pass, t_start: float,
                              initial_state: ClusterTensors | None = None,
                              ) -> tuple[ClusterTensors, OptimizerResult]:
        from ..utils.tracing import TRACER
        options = options or OptimizationOptions()
        goal_chain = list(goals) if goals is not None \
            else goals_by_priority(self._config)
        goal_chain = self._resolve_broker_sets(goal_chain, meta)
        masks = self._masks(state, meta, options)
        search_cfg = self.search_config(state)
        # fast_mode (ParameterUtils FAST_MODE_PARAM): the reference bounds
        # per-broker greedy time (fast.mode.per.broker.move.timeout.ms,
        # ResourceDistributionGoal.java:470-475). The batch-search analogue:
        # every goal runs the WIDE grid (fewer, coarser rounds) and each
        # goal's search wall-clock is capped at timeout_ms x num_brokers on
        # the bounded-dispatch path.
        fast = bool(options.fast_mode)
        if fast:
            search_cfg = self._widen(search_cfg, state.num_brokers)
        fast_budget_s = (self._config.get_long(
            "fast.mode.per.broker.move.timeout.ms") * state.num_brokers
            / 1000.0) if fast else 0.0
        # Warm-seeded solves diff against the TRUE current model: the
        # chain runs from the seeded ``state`` but proposals/stats_before
        # describe moves from reality (facade warm-start contract).
        initial = initial_state if initial_state is not None else state
        stats_before = cluster_stats(initial)
        self._maybe_record_shape(state, meta, goal_chain, masks)

        from .chain import DispatchStats
        stats = DispatchStats()
        self._dispatch_stats = stats
        self._pass_seq += 1
        self._tls.last_pass = (self._pass_seq, stats)
        megastep = self._megastep_config(
            state.num_brokers,
            density=replica_density(state, meta.num_topics))

        mesh = self._mesh
        if mesh is not None and state.num_partitions % mesh.devices.size != 0:
            # Partition axis must divide the mesh (pad via the builder's
            # partition_bucket to avoid this fallback).
            LOG.warning(
                "num_partitions %d not divisible by mesh size %d: falling "
                "back to the single-device solver for this pass",
                state.num_partitions, mesh.devices.size)
            mesh = None
        self._devices_used = int(mesh.devices.size) if mesh is not None else 1
        if mesh is not None:
            # Multi-chip production path: whole chain, one dispatch, SPMD
            # over the mesh (parallel.chain_sharded).
            from ..parallel import optimize_chain_sharded, shard_cluster
            t0 = time.time()
            state = shard_cluster(state, mesh)
            # Same large-cluster dispatch bound as the single-device path:
            # one multi-minute XLA execution trips device-runtime watchdogs.
            bounded = (self._fused_max_brokers > 0
                       and state.num_brokers > self._fused_max_brokers)
            # donate_input stays False: shard_cluster's device_put is a
            # NO-OP (alias, not copy) when the input is already sharded
            # exactly right — e.g. a caller feeding back the sharded
            # state a previous pass returned — and donating an aliased
            # buffer would delete it under ``initial`` and the caller.
            # The first bounded dispatch instead donates a cheap device
            # copy of the two mutable tensors (chain_sharded's
            # can_donate gate), same discipline as the single-device
            # chain_owns_state gate.
            # The persistent per-shape controllers ride along so mesh
            # precomputes skip the budget-relearning ramp too; the wide
            # one bills the deficit-sized count goals' dispatches.
            ctl_pair = self._controller_pair(state) if bounded \
                else (None, None)
            flight_pass.set(path="mesh", bounded=bounded)
            state, infos = optimize_chain_sharded(
                state, goal_chain, self._constraint, search_cfg,
                meta.num_topics, mesh, masks,
                dispatch_rounds=self._dispatch_rounds if bounded else 0,
                dispatch_target_s=self._dispatch_target_s,
                dispatch=ctl_pair[1 if fast else 0],
                dispatch_wide=ctl_pair[1],
                megastep=megastep, stats=stats, donate_input=False,
                flight=flight_pass)
            if not bounded:
                stats.record("chain", sum(i["rounds"] for i in infos))
                flight_pass.record_goal_infos(infos)
            goal_results = _apportioned_goal_results(
                goal_chain, infos, time.time() - t0)
            _record_goal_spans(TRACER, goal_results, search_cfg)
        elif self._fused_chain and not fast and (
                self._fused_max_brokers == 0
                or state.num_brokers <= self._fused_max_brokers):
            # Production path at small/medium scale: the whole chain in ONE
            # device dispatch (chain.chain_optimize_full).
            t0 = time.time()
            flight_pass.set(path="fused")
            state, infos = optimize_chain(
                state, goal_chain, self._constraint, search_cfg,
                meta.num_topics, masks)
            stats.record("chain", sum(i["rounds"] for i in infos))
            flight_pass.record_goal_infos(infos)
            goal_results = _apportioned_goal_results(
                goal_chain, infos, time.time() - t0)
            _record_goal_spans(TRACER, goal_results, search_cfg)
        else:
            # Per-goal bounded-dispatch path: same kernels and trajectory,
            # ≤ solver.dispatch.max.rounds search rounds per XLA execution
            # so no single dispatch runs long enough to trip a device
            # runtime's execution watchdog at 1k+ brokers (also kept for
            # equivalence tests and per-goal wall-clock attribution). Same
            # on-entry violated_before semantics as the fused path.
            dispatch_rounds = self._dispatch_rounds \
                if (self._fused_chain or fast) else 0
            # One adaptive controller across the chain AND across
            # same-shape passes (see __init__): per-round cost is a
            # property of the cluster shape, not the goal, so the budget
            # learned on goal 1 carries to goal 15 — and to the next
            # precompute of this shape.
            ctl_pair = self._controller_pair(state) if dispatch_rounds > 0 \
                else (None, None)
            # Fast mode runs every goal on the WIDENED grid, so its
            # dispatches belong to the wide controller's cost class — the
            # narrow controller's persisted budget would overshoot ~4x on
            # the first wide dispatch (the exact cross-contamination the
            # narrow/wide split exists to prevent).
            controller = ctl_pair[1] if fast else ctl_pair[0]
            # In fast mode search_cfg is already wide for every goal — a
            # second per-goal widening would compile a third grid shape.
            wide_cfg = None if fast else self._wide_config(
                search_cfg, goal_chain, state.num_brokers)
            # Wide rounds cost ~4x a narrow round, so the wide goals get
            # their OWN dispatch controller: a round budget learned on
            # cheap narrow dispatches would overshoot the wall-clock
            # target ~4x on the first wide dispatch (watchdog territory),
            # then depress the narrow goals' budget after the halving.
            # Deficit-sized count goals belong to the same wide cost
            # class: chain.deficit_sized_config can widen their
            # sources/moves past the wide grid even though they run the
            # narrow cfg, so billing them to the narrow controller would
            # recreate exactly that overshoot-then-depress cycle — and
            # persist it across same-shape passes.
            deficit_sizing = megastep.deficit_moves_cap > 0
            flight_pass.set(path="bounded" if dispatch_rounds > 0
                            else "pergoal")
            # Fingerprint goal skipping (round 18): ONE batched stats
            # program snapshots every goal's entry (violation, objective)
            # plus the goal-independent offline count and drain flag.
            # While no goal has mutated the state (chain_owns_state
            # False), each goal's entry stats come from the snapshot —
            # and a goal it shows inactive consumes zero dispatches.
            # After the first mutation the hints are stale and goals
            # dispatch their own entry stats exactly as before.
            hint_viol = hint_obj = None
            hint_off = 0
            hint_drain = None
            if self._fingerprint_skip and not fast:
                from ..warmstart import violation_fingerprint
                from .chain import chain_all_goal_stats
                av, ao, aoff = chain_all_goal_stats(
                    state, tuple(goal_chain), self._constraint,
                    meta.num_topics, masks)
                hint_viol = np.asarray(av)
                hint_obj = np.asarray(ao)
                hint_off = int(aoff)
                hint_drain = False
                if masks.excluded_replica_move_brokers is not None:
                    from .chain import excluded_hosting_replicas
                    hint_drain = bool(excluded_hosting_replicas(
                        state,
                        masks.excluded_replica_move_brokers).any())
                stats.fingerprint = violation_fingerprint(hint_viol)
            goal_results = []
            # Donation gate for the chain's FIRST mutating dispatch: until
            # some goal has actually run a dispatch, the threaded state is
            # still the caller's buffers (``initial`` feeds the proposal
            # diff) and must not be donated; afterwards every input is a
            # chain-owned intermediate.
            chain_owns_state = False
            for i, g in enumerate(goal_chain):
                t0 = time.time()
                use_wide = wide_cfg is not None and g.prefers_wide_batches
                cfg_used = wide_cfg if use_wide else search_cfg
                wide_class = use_wide or (deficit_sizing and g.count_based)
                entry = None
                if hint_viol is not None and not chain_owns_state:
                    entry = (float(hint_viol[i]), float(hint_obj[i]),
                             hint_off)
                with TRACER.span("goal.solve", goal=g.name,
                                 candidates=cfg_used.num_sources
                                 * cfg_used.num_dests) as gsp:
                    state, info = optimize_goal_in_chain(
                        state, goal_chain, i, self._constraint,
                        cfg_used, meta.num_topics, masks,
                        dispatch_rounds=dispatch_rounds,
                        dispatch=ctl_pair[1] if wide_class else controller,
                        wall_budget_s=fast_budget_s,
                        megastep=megastep, stats=stats,
                        donate_input=chain_owns_state,
                        flight=flight_pass.goal(g.name),
                        entry_stats=entry,
                        drain_hint=hint_drain if entry is not None
                        else None)
                    chain_owns_state |= info["rounds"] > 0 \
                        or info.get("direct_sweeps", 0) > 0
                    gsp.set(rounds=info["rounds"],
                            moves_applied=info["moves_applied"],
                            succeeded=info["succeeded"])
                goal_results.append(GoalResult(
                    name=g.name, is_hard=g.is_hard,
                    succeeded=info["succeeded"],
                    rounds=info["rounds"], moves_applied=info["moves_applied"],
                    residual_violation=info["residual_violation"],
                    duration_s=time.time() - t0,
                    violated_before=info["violated_on_entry"]
                    or not info["succeeded"],
                    swaps_applied=info.get("swaps_applied", 0)))

        if stats.goals_skipped:
            from ..utils.sensors import SENSORS as _S
            _S.count("solver_goals_skipped", stats.goals_skipped)
        if initial_state is not None:
            # Warm-seeded solve: the per-goal entry stats describe the
            # SEEDED search start, but the user-facing "before" picture
            # (violated_goals_before, balancedness_before) must describe
            # reality — one batched snapshot on the true initial.
            from .chain import chain_all_violations
            av0 = np.asarray(chain_all_violations(
                initial, tuple(goal_chain), self._constraint,
                meta.num_topics, masks))
            violated_before = [g.name for g, v in zip(goal_chain, av0)
                               if float(v) > 1e-6]
        else:
            violated_before = [r.name for r in goal_results
                               if r.violated_before]
        violated_after = [r.name for r in goal_results if not r.succeeded]
        with TRACER.span("analyzer.proposal_diff") as dsp:
            stats_after = cluster_stats(state)
            proposals = diff_proposals(initial, state, meta)
            dsp.set(num_proposals=len(proposals))
        _opt_span.set(num_proposals=len(proposals),
                      violated_goals_after=",".join(violated_after),
                      devices=self.solver_devices())
        # proposal-computation-timer + per-pass gauges
        # (GoalOptimizer.java:128, Sensors.md).
        from ..utils.sensors import SENSORS
        SENSORS.record_timer("analyzer_proposal_computation",
                             time.time() - t_start)
        SENSORS.gauge("analyzer_num_proposals", len(proposals))
        SENSORS.gauge("analyzer_violated_goals_after", len(violated_after))
        SENSORS.gauge("analyzer_solver_devices", self.solver_devices())
        result = OptimizerResult(
            proposals=proposals, goal_results=goal_results,
            stats_before=stats_before, stats_after=stats_after,
            violated_goals_before=violated_before,
            violated_goals_after=violated_after,
            balancedness_before=balancedness_score(
                goal_chain, set(violated_before), self._priority_weight,
                self._strictness_weight),
            balancedness_after=balancedness_score(
                goal_chain, set(violated_after), self._priority_weight,
                self._strictness_weight),
            duration_s=time.time() - t_start,
        )
        return state, result

    # -- megabatch: whole buckets of clusters in one device program --------
    def megabatch_chain(self, meta: ClusterMeta,
                        goals: Sequence[Goal] | None = None) -> tuple:
        """The resolved goal chain a megabatch slot would run — the
        grouping key component the fleet assembler compares: clusters may
        share one compiled batched program only when their resolved
        chains are identical (broker-set bindings included)."""
        goal_chain = list(goals) if goals is not None \
            else goals_by_priority(self._config)
        return tuple(self._resolve_broker_sets(goal_chain, meta))

    def optimizations_megabatch(self, items: Sequence[tuple],
                                goals: Sequence[Goal] | None = None,
                                options: OptimizationOptions | None = None,
                                width: int = 0,
                                ) -> list:
        """Solve MANY same-bucket clusters in one batched device program
        (ROADMAP item 3): every model in ``items`` — a sequence of
        ``(state, meta, cluster_id)`` or ``(state, meta, cluster_id,
        options)`` — is stacked along a leading cluster axis and the
        whole goal chain runs through the batched megastep drivers
        (chain.optimize_goal_in_chain_megabatch), so the fleet pays
        max-over-clusters rounds instead of the serial sum and ONE
        compiled program per bucket shape serves any occupancy.

        PER-ITEM options (the 4-tuple form, round 15) carry each
        cluster's own exclusion set — the fix path's recently-removed
        brokers, a future's drained brokers — into per-cluster exclusion
        MASKS stacked along the cluster axis. Mask presence is
        normalized across the batch: when any item excludes along a
        field, items without exclusions get an all-False mask (inert:
        it filters nothing), so mixed batches share one compiled mask
        layout instead of splitting into per-presence programs.

        Preconditions (the fleet assembler's grouping contract — violated
        ones raise ValueError before any device work): identical padded
        bucket shape including the replica-slot axis, identical
        ``num_topics``, an identical resolved goal chain, and no fast
        mode. ``width`` > len(items) pads the batch with inert
        zero-weight cluster slots (all-dead brokers, fully masked
        partitions) so one compiled program per bucket shape serves any
        occupancy.

        Deficit-aware count-goal sizing is forced OFF: it specializes the
        search grid to one cluster's entry violation, which cannot be
        shared across a batch. Controllers are the persistent per-shape
        pair keyed WITH the batch width (see _controller_pair).

        Returns a list aligned with ``items``: ``(final_state,
        OptimizerResult)`` per cluster, or the per-cluster Exception a
        serial solve would have raised (hard-goal failure / stats
        regression) — one cluster's failure never aborts its batchmates.
        """
        import contextlib

        import jax

        from .chain import (
            DispatchStats, inert_state_like, optimize_goal_in_chain_megabatch,
            stack_states, unstack_state,
        )
        from ..utils.flight_recorder import FLIGHT, NO_FLIGHT
        from ..utils.sensors import SENSORS, cluster_label
        from ..utils.tracing import TRACER
        from ..utils.xla_telemetry import shape_scope

        if not items:
            return []
        options = options or OptimizationOptions()
        n = len(items)
        states = [it[0] for it in items]
        metas = [it[1] for it in items]
        cluster_ids = [it[2] if len(it) > 2 else None for it in items]
        opts_list = [it[3] if len(it) > 3 and it[3] is not None else options
                     for it in items]
        # Optional per-item TRUE initial state (5th element, round 18
        # warm starts): the chain solves from the seeded ``state`` but
        # each cluster's proposal diff / stats_before / before-picture
        # use reality.
        warm_seeded = [len(it) > 4 and it[4] is not None for it in items]
        true_initials = [it[4] if w else it[0]
                         for w, it in zip(warm_seeded, items)]
        if any(o.fast_mode for o in opts_list):
            raise ValueError("megabatch does not support fast_mode")
        shape0 = jax.tree.map(lambda x: x.shape, states[0])
        for st in states[1:]:
            if jax.tree.map(lambda x: x.shape, st) != shape0:
                raise ValueError("megabatch models must share one padded "
                                 "bucket shape")
        num_topics = metas[0].num_topics
        if any(m.num_topics != num_topics for m in metas):
            raise ValueError("megabatch models must share num_topics")
        chain0 = self.megabatch_chain(metas[0], goals)
        for m in metas[1:]:
            if self.megabatch_chain(m, goals) != chain0:
                raise ValueError("megabatch models must share one resolved "
                                 "goal chain")
        goal_chain = list(chain0)

        masks_list = self._uniform_mask_presence(
            [self._masks(st, m, o)
             for st, m, o in zip(states, metas, opts_list)])

        # Device-sharded megabatch (round 23): with a mesh attached (and
        # fleet.shard.enabled) the CLUSTER axis shards across it —
        # c/ndev slots per device, batch width padded to a device
        # multiple with the same inert slots that pad occupancy (the
        # fleet/bucketing.py append-only geometry: pow2 steps of the
        # device count, so the compiled-shape set stays bounded).
        mesh = self._mesh if self._shard_enabled else None
        ndev = int(mesh.devices.size) if mesh is not None else 1
        c = max(n, int(width) or n)
        if mesh is not None:
            from ..fleet.bucketing import geometric_round_up
            c = geometric_round_up(c, ndev, 2.0)
        pad = c - n
        if pad:
            inert = inert_state_like(states[0])
            states = states + [inert] * pad
            # Pad slots need mask rows too (the stacked mask axis must
            # match the cluster axis): all-False masks matching the real
            # clusters' presence pattern — an inert slot excludes
            # nothing, and it generates no candidates anyway.
            import jax.numpy as jnp
            pad_masks = ExclusionMasks(*(
                None if f is None else jnp.zeros_like(f)
                for f in (masks_list[0].excluded_topics,
                          masks_list[0].excluded_replica_move_brokers,
                          masks_list[0].excluded_leadership_brokers)))
            masks_list = masks_list + [pad_masks] * pad
        batched_masks = self._stack_masks(masks_list)
        cluster_mask = np.concatenate([np.ones(n, dtype=bool),
                                       np.zeros(pad, dtype=bool)])

        state0 = items[0][0]
        search_cfg = self.search_config(state0)
        megastep = dataclasses.replace(
            self._megastep_config(
                state0.num_brokers,
                density=replica_density(state0, num_topics)),
            deficit_moves_cap=0)
        dispatch_rounds = max(1, self._dispatch_rounds)
        ctl_pair = self._controller_pair(state0, batch=c, devices=ndev)
        wide_cfg = self._wide_config(search_cfg, goal_chain,
                                     state0.num_brokers)

        physical = DispatchStats()
        per_cluster_stats = [DispatchStats() for _ in range(c)]
        self._dispatch_stats = physical
        t_start = time.time()

        batched = stack_states(states)
        if mesh is not None:
            from ..parallel.megabatch_sharded import (
                shard_megabatch, shard_megabatch_masks,
            )
            batched = shard_megabatch(batched, mesh)
            batched_masks = shard_megabatch_masks(batched_masks, mesh)
        self._devices_used = ndev
        initial_states = true_initials
        stats_before = [cluster_stats(st) for st in initial_states]
        self._maybe_record_shape(states[0], metas[0], goal_chain,
                                 masks_list[0], batch=c)

        # Fingerprint goal skipping, batched (round 18): one [C, G]
        # snapshot for the whole chain; a goal it shows inactive for
        # EVERY cluster consumes zero batched dispatches. Hints go stale
        # at the first mutation (chain_owns_state), like the serial path.
        hint = None
        hint_drain = None
        if self._fingerprint_skip:
            from ..warmstart import violation_fingerprint
            from .chain import (
                excluded_hosting_replicas, megabatch_all_goal_stats,
            )
            if mesh is not None:
                from ..parallel.megabatch_sharded import (
                    megabatch_all_goal_stats_sharded,
                )
                av, ao, aoff = megabatch_all_goal_stats_sharded(
                    mesh, batched, tuple(goal_chain), self._constraint,
                    num_topics, batched_masks)
            else:
                av, ao, aoff = megabatch_all_goal_stats(
                    batched, tuple(goal_chain), self._constraint,
                    num_topics, batched_masks)
            hint = (np.asarray(av), np.asarray(ao), np.asarray(aoff))
            if batched_masks.excluded_replica_move_brokers is not None:
                hint_drain = np.asarray(jax.vmap(excluded_hosting_replicas)(
                    batched,
                    batched_masks.excluded_replica_move_brokers,
                ).any(axis=(1, 2)))
            else:
                hint_drain = np.zeros(c, dtype=bool)
            physical.fingerprint = violation_fingerprint(hint[0])

        results_per_goal: list[list[dict]] = []
        durations: list[float] = []
        dead = np.zeros(c, dtype=bool)
        errors: list[Exception | None] = [None] * c
        with contextlib.ExitStack() as scopes:
            flight_passes = []
            for b in range(c):
                if not cluster_mask[b]:
                    flight_passes.append(None)
                    continue
                self._pass_seq += 1
                fp = FLIGHT.pass_scope(
                    seq=self._pass_seq,
                    shape=(state0.num_partitions, state0.num_brokers),
                    cluster=cluster_ids[b])
                scopes.enter_context(fp)
                fp.set(path="megabatch", occupancy=n, batch_width=c)
                flight_passes.append(fp)
            self._tls.last_pass = (self._pass_seq, physical)
            with TRACER.span("analyzer.megabatch", occupancy=n,
                             batch_width=c,
                             num_partitions=state0.num_partitions,
                             num_brokers=state0.num_brokers) as sp, \
                    shape_scope(state0.num_partitions, state0.num_brokers):
                chain_owns_state = False
                for i, g in enumerate(goal_chain):
                    t0 = time.time()
                    use_wide = wide_cfg is not None and g.prefers_wide_batches
                    cfg_used = wide_cfg if use_wide else search_cfg
                    flights = [
                        flight_passes[b].goal(g.name)
                        if flight_passes[b] is not None else NO_FLIGHT
                        for b in range(c)]
                    entry = None
                    if hint is not None and not chain_owns_state:
                        entry = (hint[0][:, i], hint[1][:, i], hint[2])
                    batched, infos = optimize_goal_in_chain_megabatch(
                        batched, goal_chain, i, self._constraint, cfg_used,
                        num_topics, batched_masks, cluster_mask & ~dead,
                        dispatch_rounds=dispatch_rounds,
                        dispatch=ctl_pair[1 if use_wide else 0],
                        megastep=megastep, stats=per_cluster_stats,
                        physical_stats=physical, flights=flights,
                        donate_input=chain_owns_state,
                        entry_stats=entry,
                        drain_hint=hint_drain if entry is not None
                        else None, mesh=mesh)
                    chain_owns_state |= any(
                        info["rounds"] > 0 or info.get("direct_sweeps", 0) > 0
                        for info in infos)
                    durations.append(time.time() - t0)
                    results_per_goal.append(infos)
                    for b, info in enumerate(infos):
                        if cluster_mask[b] and not dead[b] \
                                and "error" in info:
                            # The serial solve would raise HERE and leave
                            # the cluster at exactly this state; freezing
                            # it for the rest of the chain preserves that.
                            errors[b] = self._megabatch_error(info)
                            dead[b] = True
                sp.set(dispatches=physical.dispatch_count,
                       errors=int(dead[cluster_mask].sum()))
            if physical.goals_skipped:
                SENSORS.count("solver_goals_skipped",
                              physical.goals_skipped)

        # Warm-path before picture, ONE batched snapshot for every
        # warm-seeded member (a per-cluster host loop of
        # chain_all_violations would pay one device round-trip per
        # cluster — on a tunneled chip that is ~0.5 s of RTT each,
        # eroding exactly the dispatch savings warm starts buy).
        warm_violated_before: dict[int, list] = {}
        warm_rows = [b for b in range(n) if warm_seeded[b]
                     and errors[b] is None]
        if warm_rows:
            from .chain import megabatch_all_goal_stats, stack_states
            init_batch = stack_states([initial_states[b]
                                       for b in warm_rows])
            init_masks = self._stack_masks([masks_list[b]
                                            for b in warm_rows])
            av, _ao, _aoff = megabatch_all_goal_stats(
                init_batch, tuple(goal_chain), self._constraint,
                num_topics, init_masks)
            av = np.asarray(av)
            for i, b in enumerate(warm_rows):
                warm_violated_before[b] = [
                    g.name for g, v in zip(goal_chain, av[i])
                    if float(v) > 1e-6]

        out: list = []
        for b in range(n):
            cid = cluster_ids[b]
            if errors[b] is not None:
                out.append(errors[b])
                continue
            final = unstack_state(batched, b)
            goal_results = [GoalResult(
                name=g.name, is_hard=g.is_hard,
                succeeded=results_per_goal[i][b]["succeeded"],
                rounds=results_per_goal[i][b]["rounds"],
                moves_applied=results_per_goal[i][b]["moves_applied"],
                residual_violation=results_per_goal[i][b][
                    "residual_violation"],
                duration_s=durations[i],
                violated_before=results_per_goal[i][b]["violated_on_entry"]
                or not results_per_goal[i][b]["succeeded"],
                swaps_applied=results_per_goal[i][b]["swaps_applied"])
                for i, g in enumerate(goal_chain)
                if i < len(results_per_goal)]
            if b in warm_violated_before:
                # Reality-first "before" picture, from the one batched
                # snapshot above (same semantics as the serial warm
                # path).
                violated_before = warm_violated_before[b]
            else:
                violated_before = [r.name for r in goal_results
                                   if r.violated_before]
            violated_after = [r.name for r in goal_results
                              if not r.succeeded]
            with cluster_label(cid) if cid is not None \
                    else contextlib.nullcontext():
                proposals = diff_proposals(initial_states[b], final,
                                           metas[b])
                result = OptimizerResult(
                    proposals=proposals, goal_results=goal_results,
                    stats_before=stats_before[b],
                    stats_after=cluster_stats(final),
                    violated_goals_before=violated_before,
                    violated_goals_after=violated_after,
                    balancedness_before=balancedness_score(
                        goal_chain, set(violated_before),
                        self._priority_weight, self._strictness_weight),
                    balancedness_after=balancedness_score(
                        goal_chain, set(violated_after),
                        self._priority_weight, self._strictness_weight),
                    duration_s=time.time() - t_start)
                SENSORS.record_timer("analyzer_proposal_computation",
                                     time.time() - t_start)
                SENSORS.gauge("analyzer_num_proposals", len(proposals))
                SENSORS.gauge("analyzer_violated_goals_after",
                              len(violated_after))
            out.append((final, result))
        self._megabatch_cluster_stats = {
            cluster_ids[b] or b: per_cluster_stats[b].as_dict()
            for b in range(n)}
        SENSORS.observe("solver_megabatch_occupancy", float(n),
                        buckets=(1, 2, 4, 8, 16, 32, 64))
        SENSORS.gauge("solver_megabatch_width", float(c))
        return out

    def last_megabatch_cluster_stats(self) -> dict:
        """Per-cluster dispatch accounting of the LAST megabatch pass,
        split out of the batched readback (cluster id -> DispatchStats
        dict). The fleet runner reads it to report
        fleet_precompute_dispatches{cluster=} exactly."""
        return dict(getattr(self, "_megabatch_cluster_stats", {}))

    # -- prewarm (round 18, warmstart.py) ----------------------------------
    def attach_shape_registry(self, registry) -> None:
        """warmstart.ensure_prewarm's recording seam: every solve after
        this records its padded tensor signature, so a FRESH process can
        compile the whole per-shape kernel set before its first request."""
        self._shape_registry = registry

    def _maybe_record_shape(self, state, meta, goal_chain, masks,
                            batch: int = 0) -> None:
        reg = self._shape_registry
        if reg is None:
            return
        try:
            from ..warmstart import shape_signature
            sig = shape_signature(state, meta.num_topics, goal_chain,
                                  masks, batch=batch)
            if sig is not None:
                reg.record(sig)
        except Exception:  # noqa: BLE001 — recording must never break a solve
            LOG.debug("prewarm shape recording failed", exc_info=True)

    def prewarm_shape(self, entry: dict) -> bool:
        """Warm the solver-program set for ONE recorded shape signature by
        EXECUTING the production chain kernels on an inert synthetic model
        of that shape (zero-round budgets, all-dead brokers: every kernel
        compiles fully but does no search work). In-process this fills the
        jit dispatch caches the first real solve will hit; with the
        persistent compile cache enabled the XLA backend artifacts also
        land on disk, so the NEXT restart retrieves instead of compiling.
        Returns False when the entry is not reproducible here (unknown
        goal spec, or a mesh shape mismatch) — never raises for
        a merely mismatched entry; kernel failures propagate to the
        prewarm manager, which records and continues. Bound-state goal
        chains (e.g. broker-set mappings) rebuild from their signature
        specs, and mesh-sharded optimizers warm the sharded chain
        programs (_prewarm_shape_sharded) — both round-18 gaps closed in
        round 20; round 23 extends the mesh path to megabatch entries
        (the sharded megabatch chain)."""
        import jax
        from ..utils.flight_recorder import FLIGHT
        from ..warmstart import synthetic_masks, synthetic_state
        from .chain import (
            chain_all_goal_stats, chain_goal_stats, chain_optimize_full,
            chain_optimize_rounds, chain_optimize_rounds_donated,
            chain_swap_rounds, chain_swap_rounds_donated, donation_enabled,
            megabatch_all_goal_stats, megabatch_goal_stats,
            megabatch_optimize_rounds, megabatch_optimize_rounds_donated,
            megabatch_swap_rounds, megabatch_swap_rounds_donated,
            stack_states, strip_mutable,
        )
        from .goals import ALL_GOALS
        names = entry.get("goals") or []
        if not names:
            return False
        try:
            from ..warmstart import goal_from_spec
            goals = tuple(goal_from_spec(s, ALL_GOALS) for s in names)
        except Exception:  # noqa: BLE001 — unknown/irreproducible spec
            return False
        if self._mesh is not None:
            return self._prewarm_shape_sharded(entry, goals)
        state = synthetic_state(entry)
        masks = synthetic_masks(entry)
        num_topics = int(entry["num_topics"])
        batch = int(entry.get("batch") or 0)
        constraint = self._constraint
        cfg = self.search_config(state)
        megastep = self._megastep_config(state.num_brokers)
        donate = donation_enabled(megastep)
        ring_n = FLIGHT.ring_rounds if FLIGHT.enabled else 0
        wide_cfg = self._wide_config(cfg, goals, state.num_brokers)
        idx = jnp.int32(0)
        prior = jnp.asarray([False] * len(goals))
        zero = jnp.int32(0)

        def wait(out):
            jax.tree.map(lambda x: x.block_until_ready()
                         if hasattr(x, "block_until_ready") else x, out)

        if batch > 0:
            batched = stack_states([state] * batch)
            bmasks = ExclusionMasks(*(
                None if f is None else jnp.stack([f] * batch)
                for f in (masks.excluded_topics,
                          masks.excluded_replica_move_brokers,
                          masks.excluded_leadership_brokers)))
            active = jnp.zeros((batch,), bool)
            if self._fingerprint_skip:
                wait(megabatch_all_goal_stats(batched, goals, constraint,
                                              num_topics, bmasks))
            wait(megabatch_goal_stats(batched, idx, goals, constraint,
                                      num_topics, bmasks))
            for c in [cfg] + ([wide_cfg] if wide_cfg else []):
                if donate:
                    rest = dataclasses.replace(
                        batched,
                        assignment=jnp.zeros(
                            (batch, 0, batched.assignment.shape[2]),
                            batched.assignment.dtype),
                        leader_slot=jnp.zeros((batch, 0),
                                              batched.leader_slot.dtype))
                    wait(megabatch_optimize_rounds_donated(
                        jnp.copy(batched.assignment),
                        jnp.copy(batched.leader_slot), rest, active, idx,
                        prior, goals, constraint, c, num_topics, bmasks,
                        zero, ring_rounds=ring_n))
                else:
                    wait(megabatch_optimize_rounds(
                        batched, active, idx, prior, goals, constraint, c,
                        num_topics, bmasks, zero, ring_rounds=ring_n))
            if donate:
                rest = dataclasses.replace(
                    batched,
                    assignment=jnp.zeros(
                        (batch, 0, batched.assignment.shape[2]),
                        batched.assignment.dtype),
                    leader_slot=jnp.zeros((batch, 0),
                                          batched.leader_slot.dtype))
                wait(megabatch_swap_rounds_donated(
                    jnp.copy(batched.assignment),
                    jnp.copy(batched.leader_slot), rest, active, idx,
                    prior, goals, constraint, num_topics, bmasks, 8, 64,
                    zero))
            else:
                wait(megabatch_swap_rounds(batched, active, idx, prior,
                                           goals, constraint, num_topics,
                                           bmasks, 8, 64, zero))
            return True

        fused = self._fused_chain and (
            self._fused_max_brokers == 0
            or state.num_brokers <= self._fused_max_brokers)
        if fused:
            # The production path at this scale is the ONE whole-chain
            # program — the 46-63 s warmup compile of BENCH r02/r03.
            wait(chain_optimize_full(state, goals, constraint, cfg,
                                     num_topics, masks))
            return True
        # Mirror _optimizations_traced's per-goal dispatch selection
        # exactly: with the fused chain configured, oversized clusters
        # run BOUNDED dispatches (traced budget arg); with it off, the
        # per-goal drivers run unbounded (no budget arg — a different
        # trace, so a prewarm of the wrong variant would warm nothing).
        bounded = self._fused_chain and self._dispatch_rounds > 0
        if self._fingerprint_skip:
            wait(chain_all_goal_stats(state, goals, constraint, num_topics,
                                      masks))
        wait(chain_goal_stats(state, idx, goals, constraint, num_topics,
                              masks))
        for c in [cfg] + ([wide_cfg] if wide_cfg else []):
            if donate and bounded:
                wait(chain_optimize_rounds_donated(
                    jnp.copy(state.assignment), jnp.copy(state.leader_slot),
                    strip_mutable(state), idx, prior, goals, constraint, c,
                    num_topics, masks, zero, ring_rounds=ring_n))
            elif bounded:
                wait(chain_optimize_rounds(state, idx, prior, goals,
                                           constraint, c, num_topics, masks,
                                           budget=zero,
                                           ring_rounds=ring_n))
            else:
                wait(chain_optimize_rounds(state, idx, prior, goals,
                                           constraint, c, num_topics, masks,
                                           ring_rounds=ring_n))
        if donate and bounded:
            wait(chain_swap_rounds_donated(
                jnp.copy(state.assignment), jnp.copy(state.leader_slot),
                strip_mutable(state), idx, prior, goals, constraint,
                num_topics, masks, 8, 64, zero))
        elif bounded:
            wait(chain_swap_rounds(state, idx, prior, goals, constraint,
                                   num_topics, masks, budget=zero))
        else:
            wait(chain_swap_rounds(state, idx, prior, goals, constraint,
                                   num_topics, masks))
        return True

    def _prewarm_shape_sharded(self, entry: dict, goals: tuple) -> bool:
        """Mesh analogue of ``prewarm_shape`` (the round-18 documented
        gap): compile the sharded chain programs THIS process would run
        for the entry's shape by executing them on an inert sharded
        synthetic model — the whole-chain ``_make_chain_full`` program at
        fused scale, the per-goal phase kernels (donated or plain,
        matching the megastep donation mode) past fused.max.brokers,
        mirroring ``_optimize``'s mesh-branch selection exactly.
        Megabatch entries (batch > 0) warm the SHARDED megabatch chain
        (round 23, closing the round-20 "single-device machinery" gap):
        the stacked synthetic batch is placed cluster-axis-sharded and
        the shard_map stats/move/swap twins run at zero budget — the
        batch must be a device multiple (optimizations_megabatch pads
        real batches to one, so recorded signatures already are).
        Non-batch shapes whose partition axis does not divide the mesh
        stay unreproducible (the _optimize fallback would run them
        single-device anyway). Deficit-sized wide kernels still compile
        lazily at their pow2-quantized widths — sizing depends on live
        violation counts no signature can know."""
        import jax

        from ..parallel import shard_cluster
        from ..parallel.chain_sharded import (
            _make_chain_full, _make_chain_phase_kernels,
        )
        from ..warmstart import synthetic_masks, synthetic_state
        from .chain import donation_enabled, strip_mutable
        mesh = self._mesh
        state = synthetic_state(entry)
        masks = synthetic_masks(entry)
        num_topics = int(entry["num_topics"])
        cfg = self.search_config(state)
        batch = int(entry.get("batch") or 0)
        if batch > 0:
            if not self._shard_enabled or batch % mesh.devices.size != 0:
                return False
            return self._prewarm_megabatch_sharded(
                entry, goals, state, masks, num_topics, batch, cfg)
        if state.num_partitions % mesh.devices.size != 0:
            return False
        presence = (masks.excluded_topics is not None,
                    masks.excluded_replica_move_brokers is not None,
                    masks.excluded_leadership_brokers is not None)

        def wait(out):
            jax.tree.map(lambda x: x.block_until_ready()
                         if hasattr(x, "block_until_ready") else x, out)

        sharded = shard_cluster(state, mesh)
        bounded = (self._fused_max_brokers > 0
                   and state.num_brokers > self._fused_max_brokers)
        if not bounded:
            fn = _make_chain_full(mesh, goals, self._constraint, cfg,
                                  num_topics, presence, 8, 64)
            wait(fn(sharded, masks))
            return True
        megastep = self._megastep_config(state.num_brokers)
        donate = donation_enabled(megastep)
        move, swap, stats, move_d, swap_d = _make_chain_phase_kernels(
            mesh, goals, self._constraint, cfg, num_topics, presence,
            8, 64)
        idx = jnp.int32(0)
        prior = jnp.asarray([False] * len(goals))
        zero = jnp.int32(0)
        wait(stats(sharded, masks, idx))
        if donate:
            a, ls, *_ = move_d(jnp.copy(sharded.assignment),
                               jnp.copy(sharded.leader_slot),
                               strip_mutable(sharded), masks, idx, prior,
                               zero)
            wait((a, ls))
            a, ls, *_ = swap_d(jnp.copy(sharded.assignment),
                               jnp.copy(sharded.leader_slot),
                               strip_mutable(sharded), masks, idx, prior,
                               zero)
            wait((a, ls))
        else:
            wait(move(sharded, masks, idx, prior, zero))
            wait(swap(sharded, masks, idx, prior, zero))
        return True

    def _prewarm_megabatch_sharded(self, entry: dict, goals: tuple,
                                   state, masks, num_topics: int,
                                   batch: int, cfg) -> bool:
        """Warm the device-sharded megabatch kernel set for one recorded
        (shape, batch) signature: the mirror of ``prewarm_shape``'s
        batch > 0 block with every kernel routed through its shard_map
        twin, so a fresh fleet replica's first batched solve hits warm
        dispatch caches (and, with the persistent compile cache, warm
        XLA artifacts) at the mesh size it will actually run."""
        import jax

        from ..parallel.megabatch_sharded import (
            megabatch_all_goal_stats_sharded, megabatch_goal_stats_sharded,
            megabatch_optimize_rounds_donated_sharded,
            megabatch_optimize_rounds_sharded,
            megabatch_swap_rounds_donated_sharded,
            megabatch_swap_rounds_sharded, shard_megabatch,
            shard_megabatch_masks,
        )
        from ..utils.flight_recorder import FLIGHT
        from .chain import donation_enabled, stack_states
        mesh = self._mesh
        constraint = self._constraint
        megastep = self._megastep_config(state.num_brokers)
        donate = donation_enabled(megastep)
        ring_n = FLIGHT.ring_rounds if FLIGHT.enabled else 0
        wide_cfg = self._wide_config(cfg, goals, state.num_brokers)
        idx = jnp.int32(0)
        prior = jnp.asarray([False] * len(goals))
        zero = jnp.int32(0)

        def wait(out):
            jax.tree.map(lambda x: x.block_until_ready()
                         if hasattr(x, "block_until_ready") else x, out)

        batched = shard_megabatch(stack_states([state] * batch), mesh)
        bmasks = shard_megabatch_masks(ExclusionMasks(*(
            None if f is None else jnp.stack([f] * batch)
            for f in (masks.excluded_topics,
                      masks.excluded_replica_move_brokers,
                      masks.excluded_leadership_brokers))), mesh)
        active = jnp.zeros((batch,), bool)
        if self._fingerprint_skip:
            wait(megabatch_all_goal_stats_sharded(
                mesh, batched, goals, constraint, num_topics, bmasks))
        wait(megabatch_goal_stats_sharded(mesh, batched, idx, goals,
                                          constraint, num_topics, bmasks))
        for c in [cfg] + ([wide_cfg] if wide_cfg else []):
            if donate:
                rest = dataclasses.replace(
                    batched,
                    assignment=jnp.zeros(
                        (batch, 0, batched.assignment.shape[2]),
                        batched.assignment.dtype),
                    leader_slot=jnp.zeros((batch, 0),
                                          batched.leader_slot.dtype))
                wait(megabatch_optimize_rounds_donated_sharded(
                    mesh, jnp.copy(batched.assignment),
                    jnp.copy(batched.leader_slot), rest, active, idx,
                    prior, goals, constraint, c, num_topics, bmasks,
                    zero, ring_rounds=ring_n))
            else:
                wait(megabatch_optimize_rounds_sharded(
                    mesh, batched, active, idx, prior, goals, constraint,
                    c, num_topics, bmasks, zero, ring_rounds=ring_n))
        if donate:
            rest = dataclasses.replace(
                batched,
                assignment=jnp.zeros(
                    (batch, 0, batched.assignment.shape[2]),
                    batched.assignment.dtype),
                leader_slot=jnp.zeros((batch, 0),
                                      batched.leader_slot.dtype))
            wait(megabatch_swap_rounds_donated_sharded(
                mesh, jnp.copy(batched.assignment),
                jnp.copy(batched.leader_slot), rest, active, idx, prior,
                goals, constraint, num_topics, bmasks, 8, 64, zero))
        else:
            wait(megabatch_swap_rounds_sharded(
                mesh, batched, active, idx, prior, goals, constraint,
                num_topics, bmasks, 8, 64, zero))
        return True

    @staticmethod
    def _uniform_mask_presence(masks_list: list[ExclusionMasks],
                               ) -> list[ExclusionMasks]:
        """Normalize per-cluster mask presence for stacking: a field set
        by ANY cluster is filled with an inert all-False mask for the
        rest (excluding nothing is exactly what an absent mask means),
        so per-item options never split a batch by mask layout."""
        import jax.numpy as jnp
        fields = ("excluded_topics", "excluded_replica_move_brokers",
                  "excluded_leadership_brokers")
        fills = {}
        for name in fields:
            first = next((getattr(m, name) for m in masks_list
                          if getattr(m, name) is not None), None)
            if first is not None:
                fills[name] = jnp.zeros_like(first)
        if not fills:
            return masks_list
        return [ExclusionMasks(**{
            name: getattr(m, name) if getattr(m, name) is not None
            else fills.get(name) for name in fields})
            for m in masks_list]

    @staticmethod
    def _stack_masks(masks_list: list[ExclusionMasks]) -> ExclusionMasks:
        """Stack per-cluster exclusion masks along the cluster axis.
        Presence must be uniform: a field is None for every cluster or an
        array for every cluster (the batched kernels compile one mask
        layout per program)."""
        import jax.numpy as jnp

        def stack_field(name: str):
            vals = [getattr(m, name) for m in masks_list]
            present = [v is not None for v in vals]
            if not any(present):
                return None
            if not all(present):
                raise ValueError(
                    f"megabatch exclusion-mask presence for {name} must "
                    "be uniform across the batch")
            return jnp.stack(vals)

        return ExclusionMasks(
            excluded_topics=stack_field("excluded_topics"),
            excluded_replica_move_brokers=stack_field(
                "excluded_replica_move_brokers"),
            excluded_leadership_brokers=stack_field(
                "excluded_leadership_brokers"))

    @staticmethod
    def _megabatch_error(info: dict) -> Exception:
        from .chain import StatsRegressionError
        from .search import OptimizationFailureError
        cls = {"StatsRegressionError": StatsRegressionError,
               "OptimizationFailureError": OptimizationFailureError}.get(
            info.get("error_type"), RuntimeError)
        return cls(info.get("error", "megabatch cluster solve failed"))
